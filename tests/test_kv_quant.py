"""Scaled int8 paged KV + fused KV page writes (round 10).

Tier structure (the ISSUE's acceptance criteria):
  * fp-tol parity: every quantized-capable kernel mode (dma2, dma3,
    ragged, gather) dequantizes the SAME stored int8 bytes as the jnp
    oracle (`gather_kv_dequant` + `causal_attention`) — interpret mode on
    CPU, the default float tier (both sides read identical bytes, so the
    tolerance is float math, not quantization error). The quantization
    error itself is pinned separately (roundtrip RMS tier + engine-level
    greedy agreement vs a bf16-KV engine, like tests/test_kv_fp8.py).
  * fused-write byte identity: the in-kernel decode write (dma2/dma3) and
    the in-grid ragged write produce pools (and, for int8, scales)
    byte-identical to the separate-dispatch writers.
  * kv_cache_dtype=None bit identity: the default pool carries no scales
    and the decode step's numerics route through exactly the pre-round-10
    unquantized pieces.
"""

import numpy as np
import pytest

# Heavyweight tier: CPU-mesh jit compiles dominate (pytest.ini tiering).
pytestmark = pytest.mark.full

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import PRESETS
from agentic_traffic_testing_tpu.models.llama import init_params
from agentic_traffic_testing_tpu.ops.attention_backend import (
    paged_decode_attention,
)
from agentic_traffic_testing_tpu.ops.jnp_ops import causal_attention
from agentic_traffic_testing_tpu.ops.pallas.paged_attention import (
    paged_attention_decode_dma2,
    paged_attention_decode_dma3,
)
from agentic_traffic_testing_tpu.ops.pallas.ragged_paged_attention import (
    ragged_paged_attention,
    ragged_paged_attention_ref,
)
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.kv_cache import (
    KV_QMAX,
    TRASH_BLOCK,
    gather_kv_dequant,
    make_kv_cache,
    quantize_with_scale,
    write_decode_kv_full,
    write_decode_kv_full_quant,
)
from agentic_traffic_testing_tpu.runtime.request import SamplingParams
from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

CFG = PRESETS["tiny"]

DMA_KERNELS = {
    "dma2": paged_attention_decode_dma2,
    "dma3": paged_attention_decode_dma3,
}


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def _quant_pool(rng, *, L=3, kh=2, nb=12, bs=4, hd=64):
    """A random scaled int8 pool pair: plausible scales, full-range bytes."""
    kq = jnp.asarray(rng.integers(-127, 128, (L, kh, nb, bs, hd)), jnp.int8)
    vq = jnp.asarray(rng.integers(-127, 128, (L, kh, nb, bs, hd)), jnp.int8)
    ks = jnp.asarray(rng.uniform(0.004, 0.02, (L, nb, kh)), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.004, 0.02, (L, nb, kh)), jnp.float32)
    return kq, vq, ks, vs


def _tables(ctx_lens, bs, width):
    bt = np.full((len(ctx_lens), width), TRASH_BLOCK, np.int32)
    nxt = 1
    for i, ln in enumerate(ctx_lens):
        n = -(-ln // bs)
        bt[i, :n] = np.arange(nxt, nxt + n)
        nxt += n
    return jnp.asarray(bt)


def _dequant_oracle(q, kq, vq, ks, vs, bt, cl, li):
    k_all = gather_kv_dequant(kq[li], ks[li], bt).astype(q.dtype)
    v_all = gather_kv_dequant(vq[li], vs[li], bt).astype(q.dtype)
    out = causal_attention(q[:, None], k_all, v_all,
                          q_positions=(cl - 1)[:, None], kv_valid_len=cl)
    return out[:, 0]


# -- config validation -------------------------------------------------------


def test_engine_config_validates_int8_and_fused():
    EngineConfig(model="tiny", kv_cache_dtype="int8")  # accepted
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        EngineConfig(model="tiny", kv_cache_dtype="int4")
    with pytest.raises(ValueError, match="fused_kv_write"):
        EngineConfig(model="tiny", fused_kv_write=2)
    # Round 14: fused x speculation BUILDS — single-token dispatches stay
    # fused, the multi-token verify keeps its chained write sequence
    # (identity pinned in tests/test_speculative.py).
    EngineConfig(model="tiny", fused_kv_write=1, speculation="ngram")
    with pytest.raises(ValueError, match="hybrid"):
        EngineConfig(model="tiny", fused_kv_write=1, hybrid_token_budget=64,
                     kv_cache_dtype="int8")
    with pytest.raises(ValueError, match="block_size"):
        EngineConfig(model="tiny", fused_kv_write=1, hybrid_token_budget=64,
                     block_size=4)
    # The pairwise combinations stay legal.
    EngineConfig(model="tiny", fused_kv_write=1, hybrid_token_budget=64)
    EngineConfig(model="tiny", fused_kv_write=1, kv_cache_dtype="int8")


def test_int8_refuses_legacy_attention_mode(params, monkeypatch):
    """A pinned ATT_TPU_ATTENTION=dma/pallas cannot dequantize the scaled
    pool — the engine refuses at construction, not per dispatch."""
    monkeypatch.setenv("ATT_TPU_ATTENTION", "dma")
    with pytest.raises(ValueError, match="int8"):
        _engine(params, kv_cache_dtype="int8")
    monkeypatch.setenv("ATT_TPU_ATTENTION", "dma3")
    _engine(params, kv_cache_dtype="int8")  # quantized-capable mode: builds


def test_mesh_runner_refuses_int8_and_fused(params):
    class NoQuantRunner(ModelRunner):
        supports_quantized_kv = False
        supports_fused_kv_write = False

    runner = NoQuantRunner(CFG, params, decode_steps=1)
    with pytest.raises(ValueError, match="int8"):
        LLMEngine(EngineConfig(model="tiny", dtype="float32", num_blocks=16,
                               max_model_len=64, kv_cache_dtype="int8"),
                  model_cfg=CFG, runner=runner)
    with pytest.raises(ValueError, match="fused"):
        LLMEngine(EngineConfig(model="tiny", dtype="float32", num_blocks=16,
                               max_model_len=64, fused_kv_write=1),
                  model_cfg=CFG, runner=runner)
    # A fused engine also refuses an unfused supplied runner (the flag is
    # baked into the runner's compiled programs).
    plain = ModelRunner(CFG, params, decode_steps=1)
    with pytest.raises(ValueError, match="supplied runner"):
        LLMEngine(EngineConfig(model="tiny", dtype="float32", num_blocks=16,
                               max_model_len=64, fused_kv_write=1),
                  model_cfg=CFG, runner=plain)


def test_capacity_profile_accounts_for_scales():
    from agentic_traffic_testing_tpu.runtime.kv_cache import profile_num_blocks

    free = 1 << 30
    plain = profile_num_blocks(CFG, 16, free, 0.9, 1)
    scaled = profile_num_blocks(CFG, 16, free, 0.9, 1, scale_bytes_per_head=8)
    assert 0 < scaled <= plain


# -- quantization roundtrip tier ---------------------------------------------


def test_quantize_roundtrip_rms_tier():
    """Per-(page x head) symmetric int8 against the page absmax: <= ~0.5%
    relative RMS on normal data — the tier the engine-level agreement
    tests (and bench's quality gate) lean on."""
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((4, 16, 64)), jnp.float32)
    scale = jnp.max(jnp.abs(x), axis=(-2, -1), keepdims=True) / KV_QMAX
    q = quantize_with_scale(x, scale)
    back = q.astype(jnp.float32) * scale
    rms = float(jnp.sqrt(jnp.mean((back - x) ** 2))
                / jnp.sqrt(jnp.mean(x ** 2)))
    assert rms < 0.01, rms
    # All-zero pages quantize to scale 0 / values 0, never NaN.
    z = jnp.zeros((1, 16, 64), jnp.float32)
    q0 = quantize_with_scale(z, jnp.zeros((1, 1, 1), jnp.float32))
    assert int(jnp.sum(jnp.abs(q0))) == 0


# -- kernel-vs-oracle parity (int8, every quantized-capable mode) ------------


@pytest.mark.parametrize("kernel", DMA_KERNELS.values(), ids=DMA_KERNELS)
def test_int8_kernel_matches_dequant_oracle(kernel):
    rng = np.random.default_rng(0)
    kq, vq, ks, vs = _quant_pool(rng)
    ctx = [6, 11]
    bt = _tables(ctx, 4, 4)
    cl = jnp.asarray(ctx, jnp.int32)
    q = jnp.asarray(rng.standard_normal((2, 4, 64)), jnp.float32)
    li = 1
    want = _dequant_oracle(q, kq, vq, ks, vs, bt, cl, li)
    got = kernel(q, kq, vq, bt, cl, layer=li, k_scale=ks, v_scale=vs,
                 interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)
    # Unstacked (4D pool + [nb, KH] scales) — the direct-kernel shape.
    got4 = kernel(q, kq[li], vq[li], bt, cl, k_scale=ks[li], v_scale=vs[li],
                  interpret=True)
    np.testing.assert_allclose(np.asarray(got4), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_int8_gather_and_ragged_modes_match_oracle():
    rng = np.random.default_rng(1)
    kq, vq, ks, vs = _quant_pool(rng)
    ctx = [6, 11]
    bt = _tables(ctx, 4, 4)
    cl = jnp.asarray(ctx, jnp.int32)
    q = jnp.asarray(rng.standard_normal((2, 4, 64)), jnp.float32)
    li = 1
    want = _dequant_oracle(q, kq, vq, ks, vs, bt, cl, li)
    got_g = paged_decode_attention(q[:, None], kq, vq, bt, cl - 1,
                                   mode="gather", layer=li,
                                   k_scale=ks, v_scale=vs)[:, 0]
    np.testing.assert_allclose(np.asarray(got_g), np.asarray(want),
                               atol=2e-5, rtol=2e-5)
    got_r = paged_decode_attention(q[:, None], kq, vq, bt, cl - 1,
                                   mode="ragged", layer=li,
                                   k_scale=ks, v_scale=vs)[:, 0]
    np.testing.assert_allclose(np.asarray(got_r), np.asarray(want),
                               atol=2e-5, rtol=2e-5)
    # Legacy modes refuse loudly rather than upcasting raw int8 bytes.
    for mode in ("dma", "pallas", "interpret"):
        with pytest.raises(ValueError, match="int8"):
            paged_decode_attention(q[:, None], kq, vq, bt, cl - 1,
                                   mode=mode, layer=li,
                                   k_scale=ks, v_scale=vs)


def test_int8_scale_tile_covers_last_chunk():
    """Regression: with pages_per_chunk not dividing the 128-lane scale
    pad (cp=12, W=128 -> last chunk slice [120, 132) past the old Wp=128
    tile), the clamped dynamic_slice used to apply pages 116-120's scales
    to pages 120-127 — silently wrong output, no error."""
    rng = np.random.default_rng(6)
    kh, nb, bs, hd = 1, 130, 2, 64
    kq = jnp.asarray(rng.integers(-127, 128, (kh, nb, bs, hd)), jnp.int8)
    vq = jnp.asarray(rng.integers(-127, 128, (kh, nb, bs, hd)), jnp.int8)
    ks = jnp.asarray(rng.uniform(0.004, 0.02, (nb, kh)), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.004, 0.02, (nb, kh)), jnp.float32)
    w = 128
    ctx = [w * bs - 1]                                 # walks every page
    bt = jnp.asarray(np.arange(1, w + 1, dtype=np.int32)[None])
    cl = jnp.asarray(ctx, jnp.int32)
    q = jnp.asarray(rng.standard_normal((1, 2, hd)), jnp.float32)
    k_all = gather_kv_dequant(kq, ks, bt).astype(q.dtype)
    v_all = gather_kv_dequant(vq, vs, bt).astype(q.dtype)
    want = causal_attention(q[:, None], k_all, v_all,
                            q_positions=(cl - 1)[:, None],
                            kv_valid_len=cl)[:, 0]
    for kernel in DMA_KERNELS.values():
        got = kernel(q, kq, vq, bt, cl, k_scale=ks, v_scale=vs,
                     pages_per_chunk=12, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)


def test_int8_verify_layout_matches_oracle():
    """S>1 (speculative verify) over the quantized pool: dequant is
    row-independent, so the verify shape rides the same scale tiles."""
    rng = np.random.default_rng(5)
    kq, vq, ks, vs = _quant_pool(rng, nb=16, bs=4)
    b, s = 2, 3
    ctx = [6, 9]
    bt = _tables([c + s - 1 for c in ctx], 4, 6)
    cl = jnp.asarray(ctx, jnp.int32)
    q = jnp.asarray(rng.standard_normal((b, s, 4, 64)), jnp.float32)
    li = 0
    k_all = gather_kv_dequant(kq[li], ks[li], bt).astype(q.dtype)
    v_all = gather_kv_dequant(vq[li], vs[li], bt).astype(q.dtype)
    qpos = (cl - 1)[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
    want = causal_attention(q, k_all, v_all, q_positions=qpos,
                            kv_valid_len=cl + s - 1)
    for kernel in DMA_KERNELS.values():
        got = kernel(q, kq, vq, bt, cl, layer=li, k_scale=ks, v_scale=vs,
                     interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=1e-4)


def test_int8_ragged_hybrid_shape_matches_oracle():
    """Mixed decode + chunk rows over the quantized pool (the hybrid
    dispatch's exact shape), kernel vs the dequantizing ref oracle."""
    rng = np.random.default_rng(2)
    L, kh, nb, bs, hd = 2, 2, 64, 4, 64
    kq, vq, ks, vs = _quant_pool(rng, L=L, kh=kh, nb=nb, bs=bs, hd=hd)
    q_lens = (1, 1, 12)
    positions = (6, 0, 8)
    t = sum(q_lens)
    q = jnp.asarray(rng.standard_normal((t, 4, hd)), jnp.float32)
    bt = np.full((3, 16), TRASH_BLOCK, np.int32)
    nxt = 1
    for r, (ln, p0) in enumerate(zip(q_lens, positions)):
        n = -(-(p0 + ln) // bs)
        bt[r, :n] = np.arange(nxt, nxt + n)
        nxt += n
    bt = jnp.asarray(bt)
    pos = jnp.asarray(positions, jnp.int32)
    li = 1
    got = ragged_paged_attention(q, kq, vq, bt, pos, q_lens, layer=li,
                                 k_scale=ks, v_scale=vs, interpret=True)
    want = ragged_paged_attention_ref(q, kq, vq, bt, pos, q_lens, layer=li,
                                      k_scale=ks, v_scale=vs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=1e-4)


def test_fp8_dma3_and_ragged_modes_match_oracle():
    """Completes the mode x dtype matrix: tests/test_kv_fp8.py covers
    v1/dma/dma2 x fp8; dma3 and ragged dequantize the same f8 bytes."""
    rng = np.random.default_rng(4)
    L, kh, nb, bs, hd = 2, 2, 10, 4, 64
    kp = jnp.asarray(rng.standard_normal((L, kh, nb, bs, hd)),
                     jnp.float32).astype(jnp.float8_e4m3fn)
    vp = jnp.asarray(rng.standard_normal((L, kh, nb, bs, hd)),
                     jnp.float32).astype(jnp.float8_e4m3fn)
    ctx = [6, 11]
    bt = _tables(ctx, bs, 4)
    cl = jnp.asarray(ctx, jnp.int32)
    q = jnp.asarray(rng.standard_normal((2, 4, hd)), jnp.float32)
    li = 0
    want = paged_decode_attention(q[:, None], kp, vp, bt, cl - 1,
                                  mode="gather", layer=li)[:, 0]
    got3 = paged_attention_decode_dma3(q, kp, vp, bt, cl, layer=li,
                                       interpret=True)
    np.testing.assert_allclose(np.asarray(got3), np.asarray(want),
                               atol=2e-5, rtol=1e-4)
    got_r = paged_decode_attention(q[:, None], kp, vp, bt, cl - 1,
                                   mode="ragged", layer=li)[:, 0]
    np.testing.assert_allclose(np.asarray(got_r), np.asarray(want),
                               atol=2e-5, rtol=1e-4)


# -- fused-write byte identity ----------------------------------------------


@pytest.mark.parametrize("kernel", DMA_KERNELS.values(), ids=DMA_KERNELS)
def test_fused_decode_write_byte_identity_bf16(kernel):
    rng = np.random.default_rng(7)
    L, kh, nb, bs, hd = 2, 2, 10, 4, 64
    kp = jnp.asarray(rng.standard_normal((L, kh, nb, bs, hd)), jnp.bfloat16)
    vp = jnp.asarray(rng.standard_normal((L, kh, nb, bs, hd)), jnp.bfloat16)
    ctx = [6, 11]
    bt = _tables(ctx, bs, 4)
    cl = jnp.asarray(ctx, jnp.int32)
    q = jnp.asarray(rng.standard_normal((2, 4, hd)), jnp.bfloat16)
    new_k = jnp.asarray(rng.standard_normal((2, kh, hd)), jnp.float32)
    new_v = jnp.asarray(rng.standard_normal((2, kh, hd)), jnp.float32)
    li = 1
    # Separate-dispatch reference: write, then attend.
    kp2 = write_decode_kv_full(kp, jnp.int32(li), new_k, bt, cl - 1)
    vp2 = write_decode_kv_full(vp, jnp.int32(li), new_v, bt, cl - 1)
    want = kernel(q, kp2, vp2, bt, cl, layer=li, interpret=True)
    got, kp3, vp3, *_ = kernel(q, kp, vp, bt, cl, layer=li,
                               new_k=new_k, new_v=new_v, interpret=True)
    assert (np.asarray(kp3, np.float32) == np.asarray(kp2, np.float32)).all()
    assert (np.asarray(vp3, np.float32) == np.asarray(vp2, np.float32)).all()
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               atol=2e-2, rtol=2e-2)


@pytest.mark.parametrize("kernel", DMA_KERNELS.values(), ids=DMA_KERNELS)
def test_fused_decode_write_byte_identity_int8(kernel):
    """int8 + fused: the in-kernel requant write must produce pages AND
    scales byte-identical to write_decode_kv_full_quant, and the same
    call's attention must read THROUGH the fresh write (s_new override)."""
    rng = np.random.default_rng(8)
    kq, vq, ks, vs = _quant_pool(rng)
    ctx = [6, 11]
    bt = _tables(ctx, 4, 4)
    cl = jnp.asarray(ctx, jnp.int32)
    q = jnp.asarray(rng.standard_normal((2, 4, 64)), jnp.float32)
    # One loud token (exceeds every page scale) forces the requant path.
    new_k = jnp.asarray(rng.standard_normal((2, 2, 64)) * 4.0, jnp.float32)
    new_v = jnp.asarray(rng.standard_normal((2, 2, 64)) * 4.0, jnp.float32)
    li = 1
    kq2, ks2 = write_decode_kv_full_quant(kq, ks, jnp.int32(li), new_k, bt,
                                          cl - 1)
    vq2, vs2 = write_decode_kv_full_quant(vq, vs, jnp.int32(li), new_v, bt,
                                          cl - 1)
    want = _dequant_oracle(q, kq2, vq2, ks2, vs2, bt, cl, li)
    got, kq3, vq3, ks3, vs3 = kernel(q, kq, vq, bt, cl, layer=li,
                                     k_scale=ks, v_scale=vs,
                                     new_k=new_k, new_v=new_v, interpret=True)
    np.testing.assert_array_equal(np.asarray(kq3), np.asarray(kq2))
    np.testing.assert_array_equal(np.asarray(vq3), np.asarray(vq2))
    np.testing.assert_array_equal(np.asarray(ks3), np.asarray(ks2))
    np.testing.assert_array_equal(np.asarray(vs3), np.asarray(vs2))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_fused_write_refuses_verify_layout():
    rng = np.random.default_rng(9)
    kq, vq, ks, vs = _quant_pool(rng)
    bt = _tables([6, 9], 4, 4)
    cl = jnp.asarray([6, 9], jnp.int32)
    q = jnp.asarray(rng.standard_normal((2, 3, 4, 64)), jnp.float32)
    new = jnp.asarray(rng.standard_normal((2, 2, 64)), jnp.float32)
    for kernel in DMA_KERNELS.values():
        with pytest.raises(ValueError, match="single-query"):
            kernel(q, kq, vq, bt, cl, layer=0, k_scale=ks, v_scale=vs,
                   new_k=new, new_v=new, interpret=True)


def test_fused_ragged_write_byte_identity():
    """Hybrid shape (decode rows + one block-aligned chunk row): the
    in-grid ragged writes reproduce the separate-dispatch pool bytes, and
    the fused call's attention sees the fresh writes (chunk tokens attend
    earlier same-call tokens through the pool)."""
    from agentic_traffic_testing_tpu.ops.attention_backend import (
        _functional_ragged_write,
        hybrid_ragged_attention,
    )

    rng = np.random.default_rng(10)
    L, kh, h, nb, bs, hd = 2, 2, 4, 64, 8, 64
    kp = jnp.asarray(rng.standard_normal((L, kh, nb, bs, hd)), jnp.bfloat16)
    vp = jnp.asarray(rng.standard_normal((L, kh, nb, bs, hd)), jnp.bfloat16)
    q_lens = (1, 1, 16)
    positions = (6, 0, 16)   # chunk row block-aligned (16 % bs == 0)
    t = sum(q_lens)
    q = jnp.asarray(rng.standard_normal((t, h, hd)), jnp.bfloat16)
    new_k = jnp.asarray(rng.standard_normal((t, kh, hd)), jnp.float32)
    new_v = jnp.asarray(rng.standard_normal((t, kh, hd)), jnp.float32)
    bt = np.full((3, 8), TRASH_BLOCK, np.int32)
    nxt = 1
    for r, (ln, p0) in enumerate(zip(q_lens, positions)):
        n = -(-(p0 + ln) // bs)
        bt[r, :n] = np.arange(nxt, nxt + n)
        nxt += n
    bt = jnp.asarray(bt)
    pos = jnp.asarray(positions, jnp.int32)
    li = 1
    # Separate-dispatch reference: functional writes, then the ref oracle.
    kp2, vp2 = _functional_ragged_write(kp, vp, bt, pos, q_lens,
                                        jnp.int32(li), new_k, new_v)
    want = ragged_paged_attention_ref(q, kp2, vp2, bt, pos, q_lens, layer=li)
    got, kp3, vp3 = ragged_paged_attention(
        q, kp, vp, bt, pos, q_lens, layer=li,
        new_k=new_k, new_v=new_v, interpret=True)
    assert (np.asarray(kp3, np.float32) == np.asarray(kp2, np.float32)).all()
    assert (np.asarray(vp3, np.float32) == np.asarray(vp2, np.float32)).all()
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               atol=2e-2, rtol=2e-2)
    # gather-mode functional fusion returns the same pools.
    got_g, kp4, vp4 = hybrid_ragged_attention(
        q, kp, vp, bt, pos, q_lens, mode="gather", layer=li,
        new_k=new_k, new_v=new_v)
    assert (np.asarray(kp4, np.float32) == np.asarray(kp2, np.float32)).all()
    np.testing.assert_allclose(np.asarray(got_g, np.float32),
                               np.asarray(want, np.float32),
                               atol=2e-2, rtol=2e-2)
    # int8 x fused ragged refuses (a q-block cannot own a page's scale).
    ks = jnp.ones((L, nb, kh), jnp.float32)
    with pytest.raises(ValueError, match="int8"):
        ragged_paged_attention(q, kp, vp, bt, pos, q_lens, layer=li,
                               k_scale=ks, v_scale=ks,
                               new_k=new_k, new_v=new_v, interpret=True)


# -- engine-level composition -------------------------------------------------


def _engine(params, **kw):
    kw.setdefault("model", "tiny")
    kw.setdefault("dtype", "float32")
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_model_len", 128)
    return LLMEngine(EngineConfig(**kw), model_cfg=CFG, params=params)


def test_int8_pool_allocated_and_engine_decodes(params):
    eng = _engine(params, kv_cache_dtype="int8")
    assert eng.cache.k.dtype == jnp.int8
    assert eng.cache.quantized
    assert eng.cache.k_scale.shape == (CFG.num_layers, 64, CFG.num_kv_heads)
    out = eng.generate(list(range(5, 25)),
                       SamplingParams(temperature=0.0, max_tokens=8,
                                      ignore_eos=True))
    assert len(out.output_ids) == 8
    assert all(0 <= t < CFG.vocab_size for t in out.output_ids)


def test_int8_decode_tracks_bf16_kv_logits(params):
    """The int8 accuracy envelope, engine-level (the fp8 test's twin):
    first decode token matches the full-precision-KV engine and greedy
    agreement stays high on this fixed seed."""
    prompt = list(range(7, 27))
    samp = SamplingParams(temperature=0.0, max_tokens=12, ignore_eos=True)
    ref = _engine(params).generate(prompt, samp).output_ids
    got = _engine(params, kv_cache_dtype="int8").generate(
        prompt, samp).output_ids
    assert got[0] == ref[0]
    agree = sum(a == b for a, b in zip(ref, got)) / len(ref)
    assert agree >= 0.5, (ref, got)


def test_int8_composes_with_chunked_prefill_and_prefix_caching(params):
    """Long prompt through the chunk path (dequantizing prior-page gather
    + quantizing offset page writes), then a prefix-cache hit over the
    same quantized pages."""
    eng = _engine(params, kv_cache_dtype="int8", prefix_caching=True,
                  prefill_chunk_tokens=32, max_model_len=160)
    prompt = list(range(11, 107))  # 96 tokens -> 3 chunks of 32
    samp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    cold = eng.generate(prompt, samp).output_ids
    warm = eng.generate(prompt, samp).output_ids
    assert cold == warm
    # Same tokens as the unchunked int8 engine (chunk-path parity).
    solo = _engine(params, kv_cache_dtype="int8",
                   max_model_len=160).generate(prompt, samp).output_ids
    assert cold == solo


def _mixed_workload(eng):
    """Short decoding prompts + one chunking long prompt — the shape the
    hybrid planner actually fuses (mirrors tests/test_hybrid_batch.py)."""
    rng = np.random.default_rng(2)
    shorts = [rng.integers(0, CFG.vocab_size, n).tolist() for n in (6, 14)]
    long_p = rng.integers(0, CFG.vocab_size, 90).tolist()
    samp = lambda: SamplingParams(temperature=0.0, max_tokens=6,
                                  ignore_eos=True)
    reqs = [eng.add_request(p, samp()) for p in shorts]
    reqs.append(eng.add_request(long_p, samp()))
    for _ in range(10_000):
        eng.step()
        if all(r.is_finished() for r in reqs):
            break
        if not eng.has_work():
            break
    assert all(r.is_finished() for r in reqs)
    return [r.generated_ids for r in reqs]


def _hybrid_engine(params, **kw):
    kw.setdefault("model", "tiny")
    kw.setdefault("dtype", "float32")
    kw.setdefault("max_model_len", 256)
    kw.setdefault("block_size", 8)
    kw.setdefault("num_blocks", 128)
    kw.setdefault("max_num_seqs", 4)
    kw.setdefault("prefill_chunk_tokens", 32)
    return LLMEngine(EngineConfig(**kw), model_cfg=CFG, params=params)


def test_int8_composes_with_hybrid(params):
    """A genuinely FUSED hybrid dispatch over the quantized pool (separate
    quantizing writes + ragged dequant) matches the serial int8 engine."""
    want = _mixed_workload(_hybrid_engine(params, kv_cache_dtype="int8"))
    eng = _hybrid_engine(params, kv_cache_dtype="int8",
                         hybrid_token_budget=64)
    got = _mixed_workload(eng)
    assert eng.scheduler.num_scheduled_hybrid > 0, "fusion never engaged"
    assert got == want


def test_int8_composes_with_speculation(params):
    """ngram speculation over the scaled int8 pool. Unlike fp8 (where a
    rejected draft's write touches only its own slots), an int8 draft can
    inflate its page's scale and re-round settled entries, so exactness
    vs the non-speculative engine is not guaranteed in general — the pin
    is first-token identity + high greedy agreement on this fixture
    (empirically identical here)."""
    prompt = [5, 6, 7, 8] * 6
    samp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)

    def run(spec):
        return _engine(params, kv_cache_dtype="int8",
                       speculation="ngram" if spec else None,
                       spec_tokens=2).generate(prompt, samp).output_ids

    plain, spec = run(False), run(True)
    assert spec[0] == plain[0]
    agree = sum(a == b for a, b in zip(plain, spec)) / len(plain)
    assert agree >= 0.75, (plain, spec)


@pytest.mark.parametrize("kv", [None, "fp8", "int8"])
def test_fused_kv_write_token_identity(params, kv):
    """LLM_FUSED_KV_WRITE moves WHERE bytes land, never WHICH bytes:
    greedy output is identical to the separate-dispatch engine for every
    pool dtype (CPU runs the functional fusion — same contract)."""
    prompt = list(range(13, 45))
    samp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    off = _engine(params, kv_cache_dtype=kv, fused_kv_write=0).generate(
        prompt, samp).output_ids
    on = _engine(params, kv_cache_dtype=kv, fused_kv_write=1).generate(
        prompt, samp).output_ids
    assert off == on


def test_fused_hybrid_token_identity(params):
    """Fused ragged writes under a genuinely fused hybrid schedule
    reproduce the separate-dispatch engine's tokens exactly."""
    want = _mixed_workload(_hybrid_engine(params, hybrid_token_budget=64,
                                          fused_kv_write=0))
    eng = _hybrid_engine(params, hybrid_token_budget=64, fused_kv_write=1)
    got = _mixed_workload(eng)
    assert eng.scheduler.num_scheduled_hybrid > 0, "fusion never engaged"
    assert got == want


def test_default_none_path_bit_identity(params):
    """kv_cache_dtype=None pin: no scales exist anywhere, and the decode
    step's numerics are BIT-identical to a reference assembled from the
    pre-round-10 pieces (write_decode_kv_full + unquantized attention) —
    the refactor added branches, not behavior, to the default path."""
    from agentic_traffic_testing_tpu.models.llama import prefill, verify_step

    eng = _engine(params)
    assert eng.cache.k_scale is None and not eng.cache.quantized
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, CFG.vocab_size, (2, 8)), jnp.int32)
    bt = _tables([8, 8], 4, 4)
    cache = make_kv_cache(CFG, num_blocks=8, block_size=4, dtype=jnp.float32)
    lens = jnp.asarray([8, 8], jnp.int32)
    logits, cache = prefill(params, CFG, tokens, cache, bt, lens)
    nxt = jnp.argmax(logits, -1).astype(jnp.int32)
    # Fresh buffer copies per run: the jitted steps donate their cache.
    def cache_copy():
        return make_kv_cache(CFG, 8, 4, jnp.float32)._replace(
            k=jnp.array(cache.k), v=jnp.array(cache.v))

    got, got_cache = verify_step(params, CFG, nxt[:, None], cache_copy(),
                                 bt, lens)
    # Bit-identical across runs of the same compiled program (no hidden
    # data-dependent branches were added to the default path)...
    got2, got_cache2 = verify_step(params, CFG, nxt[:, None], cache_copy(),
                                   bt, lens)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(got2))
    np.testing.assert_array_equal(np.asarray(got_cache.k),
                                  np.asarray(got_cache2.k))
    # ...and the written POOL BYTES (the surface round 10 touched) match
    # the decode_step program's exactly; logits to float tolerance (the
    # two jits may fuse differently).
    from agentic_traffic_testing_tpu.models.llama import decode_step

    want, want_cache = decode_step(params, CFG, nxt, cache_copy(), bt, lens)
    np.testing.assert_allclose(np.asarray(got[:, 0]), np.asarray(want),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_array_equal(np.asarray(got_cache.k),
                                  np.asarray(want_cache.k))
    np.testing.assert_array_equal(np.asarray(got_cache.v),
                                  np.asarray(want_cache.v))
    assert got_cache.k_scale is None and want_cache.k_scale is None
    # And the default engine run is deterministic across fresh engines.
    prompt = list(range(5, 21))
    samp = SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True)
    assert (_engine(params).generate(prompt, samp).output_ids
            == _engine(params).generate(prompt, samp).output_ids)


# -- host-tier unit (quantized entries) ---------------------------------------


def test_host_store_carries_scales():
    from agentic_traffic_testing_tpu.runtime.kv_offload import HostKVStore

    k = np.zeros((2, 2, 4, 64), np.int8)
    v = np.zeros_like(k)
    ks = np.full((2, 2), 0.01, np.float32)
    store = HostKVStore(1 << 20)
    assert store.put(1, (1,), k, v, k_scale=ks, v_scale=ks)
    e = store.get(1, (1,))
    assert e is not None and e.k_scale is not None
    np.testing.assert_array_equal(e.k_scale, ks)
    # Geometry attestation: a scale-less put into a scaled store drops.
    assert not store.put(2, (2,), k, v)
    assert store.stats()["host_cache_corrupt_dropped"] == 1
    # And vice versa for a scale-less store.
    store2 = HostKVStore(1 << 20)
    assert store2.put(1, (1,), k, v)
    assert not store2.put(2, (2,), k, v, k_scale=ks, v_scale=ks)
