"""N-gram (prompt-lookup) speculative decoding.

Pins the two invariants that make speculation a pure performance knob:
  * proposal/acceptance mechanics are correct (ops/speculative.py), and
  * the engine with speculation ON emits exactly the tokens the
    non-speculative engine would — bit-identical for greedy AND for seeded
    stochastic sampling (acceptance is sample-and-compare: every emitted
    token is the target sample for its (seed, step) key, so the draft only
    affects how many tokens each dispatch keeps).
Plus multi-query (verify) support in both Pallas kernels vs the jnp oracle,
run in interpreter mode on CPU (SURVEY.md §4 kernel-test strategy).
"""

import numpy as np
import pytest

# Heavyweight tier: CPU-mesh jit compiles dominate (pytest.ini tiering).
pytestmark = pytest.mark.full

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import PRESETS
from agentic_traffic_testing_tpu.models.llama import init_params
from agentic_traffic_testing_tpu.ops.jnp_ops import causal_attention
from agentic_traffic_testing_tpu.ops.pallas.paged_attention import (
    paged_attention_decode,
    paged_attention_decode_dma,
)
from agentic_traffic_testing_tpu.ops.speculative import (
    accept_counts,
    propose_ngram,
    update_history,
)
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.kv_cache import TRASH_BLOCK, gather_kv
from agentic_traffic_testing_tpu.runtime.request import SamplingParams
from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

CFG = PRESETS["tiny"]


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0), dtype=jnp.float32)


# ---------------------------------------------------------------------------
# proposal / acceptance mechanics
# ---------------------------------------------------------------------------


def _hist(rows, l=32):
    h = np.zeros((len(rows), l), np.int32)
    pos = []
    for i, row in enumerate(rows):
        h[i, : len(row)] = row
        pos.append(len(row) - 1)
    return jnp.asarray(h), jnp.asarray(pos, jnp.int32)


def test_propose_ngram_finds_latest_match():
    # trailing 2-gram (7, 8) occurred earlier, followed by 9, 4, 5
    hist, pos = _hist([[1, 7, 8, 9, 4, 5, 6, 7, 8]])
    drafts = propose_ngram(hist, pos, num_drafts=3, ngram=2)
    assert drafts.tolist() == [[9, 4, 5]]


def test_propose_ngram_prefers_most_recent_occurrence():
    # (5, 1) appears twice; the later one is followed by 3 not 2
    hist, pos = _hist([[5, 1, 2, 5, 1, 3, 9, 5, 1]])
    drafts = propose_ngram(hist, pos, num_drafts=1, ngram=2)
    assert drafts.tolist() == [[3]]


def test_propose_ngram_no_match_falls_back_to_last_token():
    hist, pos = _hist([[1, 2, 3, 4, 5, 6]])
    drafts = propose_ngram(hist, pos, num_drafts=3, ngram=3)
    assert drafts.tolist() == [[6, 6, 6]]


def test_propose_ngram_clamps_drafts_to_known_history():
    # match ends one token before the suffix: only 1 real continuation known
    hist, pos = _hist([[4, 9, 4, 9]])  # trailing (4,9) matches at j=1
    drafts = propose_ngram(hist, pos, num_drafts=3, ngram=2)
    # continuation = hist[2:] = [4, 9] then clamped repeats of the last token
    assert drafts.tolist() == [[4, 9, 9]]


def test_propose_ngram_short_history_is_safe():
    hist, pos = _hist([[3]])
    drafts = propose_ngram(hist, pos, num_drafts=2, ngram=3)
    assert drafts.shape == (1, 2)  # fallback path; values from known history
    assert drafts.tolist() == [[3, 3]]


def test_accept_counts():
    sampled = jnp.asarray([[5, 6, 7, 8],    # all drafts right
                           [5, 9, 7, 8],    # first right, second wrong
                           [1, 2, 3, 4]])   # first wrong
    drafts = jnp.asarray([[5, 6, 7],
                          [5, 6, 7],
                          [9, 9, 9]])
    assert accept_counts(sampled, drafts).tolist() == [4, 2, 1]


def test_update_history_writes_after_position():
    hist, pos = _hist([[1, 2, 3]], l=8)
    out = update_history(hist, jnp.asarray([[7, 8]], jnp.int32), pos)
    assert out.tolist() == [[1, 2, 3, 7, 8, 0, 0, 0]]


# ---------------------------------------------------------------------------
# engine equivalence: speculation is a pure perf knob
# ---------------------------------------------------------------------------


def make_engine(params, *, speculation=None, spec_tokens=3, decode_steps=2,
                **kw):
    kw.setdefault("model", "tiny")
    kw.setdefault("dtype", "float32")
    kw.setdefault("max_model_len", 128)
    kw.setdefault("block_size", 8)
    kw.setdefault("num_blocks", 96)
    kw.setdefault("max_num_seqs", 4)
    ecfg = EngineConfig(decode_steps=decode_steps, speculation=speculation,
                        spec_tokens=spec_tokens, **kw)
    runner = ModelRunner(CFG, params, decode_steps=decode_steps,
                         spec_tokens=(spec_tokens if speculation else 0))
    return LLMEngine(ecfg, model_cfg=CFG, runner=runner)


def run_all(engine, reqs):
    for _ in range(10_000):
        engine.step()
        if all(r.is_finished() for r in reqs):
            return
        if not engine.has_work():
            break
    assert all(r.is_finished() for r in reqs), [r.state for r in reqs]


# A prompt with verbatim repetition (the n-gram lookup's happy path) and one
# without; both must round-trip identically.
REPETITIVE = [11, 12, 13, 14, 15, 11, 12, 13, 14, 15, 11, 12, 13]
PLAIN = list(range(40, 60))


@pytest.mark.parametrize("prompt", [REPETITIVE, PLAIN], ids=["repeat", "plain"])
@pytest.mark.parametrize("temperature", [0.0, 0.7], ids=["greedy", "sampled"])
def test_spec_output_identical_to_plain_decode(params, prompt, temperature):
    samp = SamplingParams(max_tokens=24, temperature=temperature, seed=7,
                          ignore_eos=True)
    want = make_engine(params).generate(prompt, samp).generated_ids
    got = make_engine(params, speculation="ngram").generate(prompt, samp).generated_ids
    assert got == want


def test_spec_batch_identical_and_counters(params):
    prompts = [REPETITIVE, PLAIN, [7] * 12, list(range(80, 96))]
    samp = lambda: SamplingParams(max_tokens=16, temperature=0.0, ignore_eos=True)

    plain = make_engine(params)
    want = [plain.add_request(p, samp()) for p in prompts]
    run_all(plain, want)

    spec = make_engine(params, speculation="ngram")
    got = [spec.add_request(p, samp()) for p in prompts]
    run_all(spec, got)

    for w, g in zip(want, got):
        assert g.generated_ids == w.generated_ids
    # Acceptance accounting advanced, and emitted >= iterations (>=1/step).
    assert spec.spec_iters > 0
    assert spec.spec_emitted >= spec.spec_iters


def test_spec_accepts_on_repetitive_text(params):
    """The whole point: repetitive context must yield >1 token/verify-step."""
    eng = make_engine(params, speculation="ngram")
    req = eng.generate([21, 22, 23, 24] * 8,
                       SamplingParams(max_tokens=32, temperature=0.0,
                                      ignore_eos=True))
    assert len(req.generated_ids) == 32
    # Greedy decode of a tiny random-init model on a periodic prompt settles
    # into a loop; prompt-lookup must exploit it.
    assert eng.spec_emitted / eng.spec_iters > 1.2


def test_spec_at_max_model_len_identical(params):
    """Draft KV writes past the block table's capacity must not corrupt live
    context: a request generating right up to max_model_len (full table, so
    OOB writes would clamp onto its own tail block) must emit exactly what
    plain decode emits."""
    kw = dict(max_model_len=32, block_size=8, num_blocks=16, decode_steps=2)
    prompt = [11, 12, 13, 14, 15] * 4  # repetitive -> drafts accepted near cap
    samp = lambda: SamplingParams(max_tokens=64, temperature=0.0,
                                  ignore_eos=True)  # runs into the length cap
    want = make_engine(params, **kw).generate(prompt, samp())
    got = make_engine(params, speculation="ngram", **kw).generate(prompt, samp())
    assert want.total_len == 32
    assert got.generated_ids == want.generated_ids


def test_spec_stop_token_exact(params):
    """EOS inside an accepted draft run must stop the request on the token."""
    eng = make_engine(params, speculation="ngram")
    req = eng.generate(REPETITIVE,
                       SamplingParams(max_tokens=40, temperature=0.0,
                                      ignore_eos=True))
    # Pick a stop token whose FIRST occurrence is mid-stream (a repetitive
    # prompt makes early tokens recur, and the engine rightly stops at the
    # first occurrence — the old fixed index 9 happened to pick a token
    # that also appeared at index 0, asserting the wrong prefix).
    candidates = [(i, t) for i, t in enumerate(req.generated_ids)
                  if 2 <= i < len(req.generated_ids) - 1
                  and t not in req.generated_ids[:i]]
    if not candidates:
        pytest.skip("stream has no mid-stream first-occurrence token "
                    "(fully cyclic from the start under this seed)")
    # Prefer a token that also occurs in the prompt: the ngram drafter
    # copies history continuations, so a prompt token CAN land inside an
    # accepted draft run (the docstring's scenario) — a token new to the
    # whole history can only ever be the step's target-sampled correction.
    stop_at, tok = next(((i, t) for i, t in candidates if t in REPETITIVE),
                        candidates[0])
    eng2 = make_engine(params, speculation="ngram")
    req2 = eng2.generate(REPETITIVE,
                         SamplingParams(max_tokens=40, temperature=0.0,
                                        stop_token_ids=[tok]))
    assert req2.generated_ids == req.generated_ids[: stop_at + 1]


# ---------------------------------------------------------------------------
# multi-query (verify) paged-attention kernels vs oracle
# ---------------------------------------------------------------------------

KERNELS = {"v1": paged_attention_decode, "dma": paged_attention_decode_dma}


@pytest.mark.parametrize("kernel", KERNELS.values(), ids=KERNELS)
@pytest.mark.parametrize(
    "b,s,h,kh,hd,bs,ctx_lens",
    [
        (2, 4, 4, 2, 64, 4, [5, 9]),       # GQA 2:1
        (1, 2, 8, 1, 128, 4, [13]),        # MQA, hd=128
        (3, 3, 4, 4, 64, 8, [1, 8, 17]),   # MHA, boundary lengths
    ],
)
def test_multiquery_kernel_matches_oracle(kernel, b, s, h, kh, hd, bs, ctx_lens):
    rng = np.random.default_rng(11)
    # blocks must cover ctx + s - 1 slots: verify writes draft KV that far
    blocks_per = [-(-(ln + s - 1) // bs) for ln in ctx_lens]
    max_blocks = max(blocks_per) + 1
    num_blocks = 1 + sum(blocks_per) + 1
    q = jnp.asarray(rng.standard_normal((b, s, h, hd)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((kh, num_blocks, bs, hd)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((kh, num_blocks, bs, hd)), jnp.float32)
    bt = np.full((b, max_blocks), TRASH_BLOCK, np.int32)
    nxt = 1
    for i, n in enumerate(blocks_per):
        bt[i, :n] = np.arange(nxt, nxt + n)
        nxt += n
    bt = jnp.asarray(bt)
    cl = jnp.asarray(ctx_lens, jnp.int32)

    got = kernel(q, kp, vp, bt, cl, interpret=True)

    k_all = gather_kv(kp, bt)
    v_all = gather_kv(vp, bt)
    q_pos = (cl - 1)[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
    want = causal_attention(q, k_all, v_all, q_positions=q_pos,
                            kv_valid_len=cl + s - 1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)
