"""N-gram (prompt-lookup) speculative decoding — the round-14 composable split.

Pins the invariants that make speculation a pure performance knob:
  * host-side proposal + device-side value-aligned acceptance mechanics
    are correct (ops/speculative.py), and
  * the engine with speculation ON emits exactly the tokens the
    non-speculative engine would on these bounded-horizon fixtures —
    for greedy AND seeded sampling (acceptance is sample-and-compare:
    every emitted token is the target sample for its (seed, step) key,
    so the draft only affects how many tokens each dispatch keeps; at
    much longer horizons the committed-KV byte drift ops/speculative.py
    documents can flip a near-tie even in fp32) — for the plain engine
    AND for every round-14 composition: hybrid batching, the overlapped
    loop, the scaled int8 pool, fused KV writes, the pipelined prefill,
    and live migration, each under churn (EOS mid-batch, admission
    mid-decode, abort).
  * rejected KV appends roll back: the committed pool after a speculative
    dispatch is BYTE-identical to the serial loop's, on bf16 and int8
    pools (the accepted-prefix commit — ops/speculative.rollback_commit).
  * speculation=None keeps the non-speculative paths untouched: no
    ops/speculative code runs anywhere (monkeypatch-never-invoked pin).
Plus multi-query (verify) support in both Pallas kernels vs the jnp oracle,
run in interpreter mode on CPU (SURVEY.md §4 kernel-test strategy).
"""

import numpy as np
import pytest

# Heavyweight tier: CPU-mesh jit compiles dominate (pytest.ini tiering).
pytestmark = pytest.mark.full

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import PRESETS
from agentic_traffic_testing_tpu.models.llama import init_params
from agentic_traffic_testing_tpu.ops.jnp_ops import causal_attention
from agentic_traffic_testing_tpu.ops.pallas.paged_attention import (
    paged_attention_decode,
    paged_attention_decode_dma,
)
from agentic_traffic_testing_tpu.ops.speculative import (
    accept_counts,
    align_drafts,
    propose_ngram_host,
    propose_stream,
)
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.kv_cache import TRASH_BLOCK, gather_kv
from agentic_traffic_testing_tpu.runtime.request import (
    FinishReason,
    SamplingParams,
)
from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

from token_utils import pick_midstream_stop

CFG = PRESETS["tiny"]


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0), dtype=jnp.float32)


# ---------------------------------------------------------------------------
# host-side proposal mechanics (plain numpy)
# ---------------------------------------------------------------------------


def test_propose_finds_latest_match():
    # trailing 2-gram (7, 8) occurred earlier, followed by 9, 4, 5
    hist = [1, 7, 8, 9, 4, 5, 6, 7, 8]
    assert propose_ngram_host(hist, 3, ngram=2) == [9, 4, 5]


def test_propose_prefers_most_recent_occurrence():
    # (5, 1) appears twice; the later one is followed by 3 not 2
    hist = [5, 1, 2, 5, 1, 3, 9, 5, 1]
    assert propose_ngram_host(hist, 1, ngram=2) == [3]


def test_propose_no_match_falls_back_to_last_token():
    assert propose_ngram_host([1, 2, 3, 4, 5, 6], 3, ngram=3) == [6, 6, 6]


def test_propose_clamps_to_known_history():
    # match ends one token before the suffix: only 1 real continuation known
    hist = [4, 9, 4, 9]  # trailing (4,9) matches at j=1
    # continuation = hist[2:] = [4, 9] then clamped repeats of the last token
    assert propose_ngram_host(hist, 3, ngram=2) == [4, 9, 9]


def test_propose_short_history_is_safe():
    assert propose_ngram_host([3], 2, ngram=3) == [3, 3]
    assert propose_ngram_host([], 2, ngram=3) == [0, 0]


def test_propose_window_bounds_the_scan():
    # The early occurrence of (7, 8) sits outside a 4-token window: the
    # bounded scan must miss it and fall back to last-token repeats.
    hist = [1, 7, 8, 9, 4, 5, 6, 7, 8]
    assert propose_ngram_host(hist, 2, ngram=2, window=4) == [8, 8]
    assert propose_ngram_host(hist, 2, ngram=2, window=0) == [9, 4]
    # A window large enough to see the match behaves like the full scan.
    assert propose_ngram_host(hist, 2, ngram=2, window=7) == [9, 4]


def test_history_tail_bounds_and_matches_full_concat():
    """The engine's per-dispatch host term: with a window the tail slice
    must be O(window) AND propose identically to the full concatenation
    (the un-scanned prefix can never change a windowed match)."""
    from agentic_traffic_testing_tpu.ops.speculative import history_tail

    prompt, out = list(range(100, 400)), [7, 8, 9, 7, 8]
    tail = history_tail(prompt, out, ngram=2, window=16)
    assert len(tail) == 18  # window + ngram, not len(prompt) + len(out)
    assert tail == (prompt + out)[-18:]
    assert (propose_ngram_host(tail, 3, ngram=2, window=16)
            == propose_ngram_host(prompt + out, 3, ngram=2, window=16))
    # Window straddling the prompt/output boundary.
    short_out = [7]
    t2 = history_tail(prompt, short_out, ngram=2, window=4)
    assert t2 == (prompt + short_out)[-6:]
    # No window -> the full history (the unbounded scan needs it).
    assert history_tail([1, 2], [3], ngram=3) == [1, 2, 3]


def test_propose_stream_anchors_and_pads():
    streams = propose_stream([[1, 7, 8, 9, 7, 8]], padded_batch=3,
                             length=4, ngram=2)
    assert streams.shape == (3, 4)
    # stream[0] = last known token; continuation after the j=2 match = 9...
    assert streams[0].tolist() == [8, 9, 7, 8]
    assert streams[1].tolist() == [0, 0, 0, 0]  # padding lane


def test_align_drafts_first_occurrence_and_fallbacks():
    stream = jnp.asarray([[5, 6, 7, 5, 9, 9, 9, 9],
                          [1, 2, 3, 4, 5, 6, 7, 8],
                          [1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    toks = jnp.asarray([5, 7, 99], jnp.int32)
    got = align_drafts(stream, toks, 3)
    assert got[0].tolist() == [6, 7, 5]      # first occurrence of 5 wins
    assert got[1].tolist() == [8, 8, 8]      # clamped onto the stream end
    assert got[2].tolist() == [99, 99, 99]   # miss -> repeat-last fallback


def test_accept_counts():
    sampled = jnp.asarray([[5, 6, 7, 8],    # all drafts right
                           [5, 9, 7, 8],    # first right, second wrong
                           [1, 2, 3, 4]])   # first wrong
    drafts = jnp.asarray([[5, 6, 7],
                          [5, 6, 7],
                          [9, 9, 9]])
    assert accept_counts(sampled, drafts).tolist() == [4, 2, 1]


# ---------------------------------------------------------------------------
# engine equivalence: speculation is a pure perf knob
# ---------------------------------------------------------------------------


def make_engine(params, *, speculation=None, spec_tokens=3, decode_steps=2,
                fused_kv_write=0, **kw):
    kw.setdefault("model", "tiny")
    kw.setdefault("dtype", "float32")
    kw.setdefault("max_model_len", 128)
    kw.setdefault("block_size", 8)
    kw.setdefault("num_blocks", 96)
    kw.setdefault("max_num_seqs", 4)
    ecfg = EngineConfig(decode_steps=decode_steps, speculation=speculation,
                        spec_tokens=spec_tokens,
                        fused_kv_write=fused_kv_write, **kw)
    runner = ModelRunner(CFG, params, decode_steps=decode_steps,
                         spec_tokens=(spec_tokens if speculation else 0),
                         fused_kv_write=bool(fused_kv_write))
    return LLMEngine(ecfg, model_cfg=CFG, runner=runner)


def run_all(engine, reqs):
    for _ in range(10_000):
        engine.step()
        if all(r.is_finished() for r in reqs):
            return
        if not engine.has_work():
            break
    assert all(r.is_finished() for r in reqs), [r.state for r in reqs]


# A prompt with verbatim repetition (the n-gram lookup's happy path) and one
# without; both must round-trip identically.
REPETITIVE = [11, 12, 13, 14, 15, 11, 12, 13, 14, 15, 11, 12, 13]
PLAIN = list(range(40, 60))


@pytest.mark.parametrize("prompt", [REPETITIVE, PLAIN], ids=["repeat", "plain"])
@pytest.mark.parametrize("temperature", [0.0, 0.7], ids=["greedy", "sampled"])
def test_spec_output_identical_to_plain_decode(params, prompt, temperature):
    samp = SamplingParams(max_tokens=24, temperature=temperature, seed=7,
                          ignore_eos=True)
    want = make_engine(params).generate(prompt, samp).generated_ids
    got = make_engine(params, speculation="ngram").generate(prompt, samp).generated_ids
    assert got == want


def test_spec_batch_identical_and_counters(params):
    prompts = [REPETITIVE, PLAIN, [7] * 12, list(range(80, 96))]
    samp = lambda: SamplingParams(max_tokens=16, temperature=0.0, ignore_eos=True)

    plain = make_engine(params)
    want = [plain.add_request(p, samp()) for p in prompts]
    run_all(plain, want)

    spec = make_engine(params, speculation="ngram")
    got = [spec.add_request(p, samp()) for p in prompts]
    run_all(spec, got)

    for w, g in zip(want, got):
        assert g.generated_ids == w.generated_ids
    # Acceptance accounting advanced, emitted >= rounds (>= 1/round), and
    # the draft ledger is coherent: γ drafts per consumed round; accepted
    # counts at VERIFICATION level (m-1 per round), so it can only exceed
    # emitted - rounds when a stop/length truncates a round's emission
    # mid-row — never the reverse.
    assert spec.spec_iters > 0
    assert spec.spec_emitted >= spec.spec_iters
    assert spec.spec_drafted == spec.spec_iters * spec.cfg.spec_tokens
    assert (spec.spec_emitted - spec.spec_iters <= spec.spec_accepted
            <= spec.spec_drafted)


def test_spec_accepts_on_repetitive_text(params):
    """The whole point: repetitive context must yield >1 token/verify-step."""
    eng = make_engine(params, speculation="ngram")
    req = eng.generate([21, 22, 23, 24] * 8,
                       SamplingParams(max_tokens=32, temperature=0.0,
                                      ignore_eos=True))
    assert len(req.generated_ids) == 32
    # Greedy decode of a tiny random-init model on a periodic prompt settles
    # into a loop; prompt-lookup must exploit it.
    assert eng.spec_emitted / eng.spec_iters > 1.2
    assert eng.spec_accepted > 0


def test_spec_at_max_model_len_identical(params):
    """Draft KV writes past the block table's capacity must not corrupt live
    context: a request generating right up to max_model_len (full table, so
    OOB writes would clamp onto its own tail block) must emit exactly what
    plain decode emits."""
    kw = dict(max_model_len=32, block_size=8, num_blocks=16, decode_steps=2)
    prompt = [11, 12, 13, 14, 15] * 4  # repetitive -> drafts accepted near cap
    samp = lambda: SamplingParams(max_tokens=64, temperature=0.0,
                                  ignore_eos=True)  # runs into the length cap
    want = make_engine(params, **kw).generate(prompt, samp())
    got = make_engine(params, speculation="ngram", **kw).generate(prompt, samp())
    assert want.total_len == 32
    assert got.generated_ids == want.generated_ids


def test_spec_stop_token_exact(params):
    """EOS inside an accepted draft run must stop the request on the token.

    The stop-token scan is the SHARED helper (tests/token_utils.py —
    first-occurrence semantics): the multi-token accept path reuses it,
    never forks it."""
    eng = make_engine(params, speculation="ngram")
    req = eng.generate(REPETITIVE,
                       SamplingParams(max_tokens=40, temperature=0.0,
                                      ignore_eos=True))
    picked = pick_midstream_stop(req.generated_ids, REPETITIVE)
    if picked is None:
        pytest.skip("stream has no mid-stream first-occurrence token "
                    "(fully cyclic from the start under this seed)")
    stop_at, tok = picked
    eng2 = make_engine(params, speculation="ngram")
    req2 = eng2.generate(REPETITIVE,
                         SamplingParams(max_tokens=40, temperature=0.0,
                                        stop_token_ids=[tok]))
    assert req2.generated_ids == req.generated_ids[: stop_at + 1]


# ---------------------------------------------------------------------------
# round-14 compositions: identity vs the serial loop under churn
# ---------------------------------------------------------------------------

CHURN_PROMPTS = (REPETITIVE, PLAIN, [7] * 12, [21, 22, 23, 24] * 5)


def _churn_workload(eng, stop_tok, late_prompt):
    """EOS mid-batch (a reachable stop token on greedy lanes), admission
    mid-decode (a late arrival past the initial wave), abort — the three
    churn shapes every composed feature must reconcile identically."""
    def sampling(i):
        if i % 2 == 0:
            return SamplingParams(temperature=0.0, max_tokens=14 - (i % 3),
                                  stop_token_ids=[stop_tok])
        return SamplingParams(temperature=0.8, top_k=20, seed=5 + i,
                              max_tokens=8 + (i % 4), ignore_eos=True)

    reqs = [eng.add_request(p, sampling(i))
            for i, p in enumerate(CHURN_PROMPTS)]
    for _ in range(4):
        eng.step()
    eng.abort_request(reqs[1])
    late = eng.add_request(late_prompt, SamplingParams(
        temperature=0.0, max_tokens=10, ignore_eos=True))
    run_all(eng, [r for r in reqs if r is not reqs[1]] + [late])
    return [r.generated_ids for r in reqs if r is not reqs[1]] + [
        late.generated_ids]


COMPOSITIONS = {
    # Each newly-composed feature, individually enabled (the ISSUE-14
    # acceptance list) — plus the pipelined prefill, whose refusal died
    # with the synchronous spec-prefill readback.
    "hybrid": dict(hybrid_token_budget=48, prefill_chunk_tokens=16,
                   max_model_len=256, num_blocks=256),
    "overlap": dict(decode_overlap=1),
    "int8": dict(kv_cache_dtype="int8"),
    "fused": dict(fused_kv_write=1),
    "pipeline": dict(prefill_pipeline_chunks=2),
}


@pytest.mark.parametrize("feature", sorted(COMPOSITIONS))
def test_spec_composition_identical_under_churn(params, feature):
    kw = COMPOSITIONS[feature]
    # The stop token comes from a deterministic greedy probe on the PLAIN
    # serial engine, so both arms chase the same reachable EOS.
    probe = make_engine(params, **kw).generate(
        REPETITIVE, SamplingParams(temperature=0.0, max_tokens=14,
                                   ignore_eos=True))
    stop_tok = probe.output_ids[len(probe.output_ids) // 2]
    late = REPETITIVE[:9]

    want_eng = make_engine(params, **kw)
    want = _churn_workload(want_eng, stop_tok, late)
    got_eng = make_engine(params, speculation="ngram", **kw)
    got = _churn_workload(got_eng, stop_tok, late)
    assert got == want
    assert got_eng.spec_iters > 0
    if feature == "hybrid":
        assert got_eng.scheduler.num_scheduled_hybrid > 0, \
            "fusion never engaged — the composition was not exercised"
    if feature == "overlap":
        assert got_eng.num_overlap_dispatches > 0, \
            "the predicted-composition fast path never engaged"
        assert got_eng.num_overlap_mispredicts >= 1, \
            "churn never landed with speculative dispatches in flight"


def test_spec_migration_identity(params):
    """Checkpoint a speculative stream mid-decode, adopt it on another
    speculative engine, full sequence identical to the uninterrupted run
    — the host-side history + rejection rollback are what make the
    plain-decode checkpoint rule cover speculation unchanged."""
    kw = dict(migration=1, block_size=16, max_model_len=256, num_blocks=128)
    samp = lambda: SamplingParams(temperature=0.0, max_tokens=14,
                                  ignore_eos=True)
    prompt = [31, 32, 33, 34] * 6
    base = make_engine(params, speculation="ngram", **kw).generate(
        prompt, samp()).generated_ids
    src = make_engine(params, speculation="ngram", **kw)
    dst = make_engine(params, speculation="ngram", **kw)
    req = src.add_request(prompt, samp())
    for _ in range(2000):
        src.step()
        if req.sampling_step >= 5:
            break
    assert req.sampling_step >= 5
    plan = src.checkpoint_request(req, trigger="drain")
    assert plan is not None and plan.decodable
    assert req.finish_reason is FinishReason.MIGRATED
    adopted = dst.adopt_request(plan)
    run_all(dst, [adopted])
    assert adopted.generated_ids == base
    # Cross-check against the serial loop too: migration did not launder
    # a speculative divergence through the folded prompt.
    serial = make_engine(params, **kw).generate(prompt, samp()).generated_ids
    assert base == serial


# ---------------------------------------------------------------------------
# rejection rollback: committed KV is byte-identical to the serial loop's
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("pool", ["f32", "int8"],
                         ids=["bf16-class", "int8"])
def test_spec_rollback_kv_byte_identity(params, pool):
    """Reject-independence: two speculative dispatches whose streams agree
    on the accepted prefix but differ WILDLY in their rejected draft
    content commit byte-identical pools (pages AND int8 scale pairs) —
    the rejected appends (which land before attention and, on int8,
    requant their page) left NOTHING behind. The trash block is excluded:
    rejected replay slots mask to it (garbage by contract, never read
    unmasked), exactly like every other masked write in the engine."""
    from agentic_traffic_testing_tpu.runtime.kv_cache import make_kv_cache
    from agentic_traffic_testing_tpu.runtime.runner import SamplingArrays

    quantized = pool == "int8"
    bs, nb, tt = 8, 12, 16
    serial = ModelRunner(CFG, params, decode_steps=1)
    spec = ModelRunner(CFG, params, decode_steps=2, spec_tokens=3)
    prompt = np.zeros((1, tt), np.int32)
    prompt[0, :13] = REPETITIVE
    tables = np.full((1, 8), TRASH_BLOCK, np.int32)
    tables[0, :6] = np.arange(1, 7)
    tables = jnp.asarray(tables)
    seq = jnp.asarray([13], jnp.int32)
    samp = SamplingArrays(temperature=jnp.zeros((1,), jnp.float32),
                          top_k=jnp.zeros((1,), jnp.int32),
                          top_p=jnp.ones((1,), jnp.float32),
                          seeds=jnp.zeros((1,), jnp.int32))

    def fresh():
        dtype = jnp.int8 if quantized else jnp.float32
        cache = make_kv_cache(CFG, nb, bs, dtype, quantized=quantized)
        state, cache, out = serial.prefill(
            jnp.asarray(prompt), cache, tables, seq, samp,
            jnp.zeros((1,), jnp.int32))
        return state, cache

    # Serial oracle: the greedy continuation (what verification accepts).
    st, cache_a = fresh()
    serial_toks = []
    for _ in range(8):
        st, cache_a, out = serial.decode(cache_a, tables, st, samp)
        serial_toks.append(int(out[0, 0]))

    def spec_dispatch(garbage_tok):
        """One 2-round γ=3 dispatch whose stream walks the true
        continuation for 3 tokens then proposes `garbage_tok` — partial
        acceptance, so rejected appends land and must roll back."""
        st2, cache_b = fresh()
        stream = np.zeros((1, 12), np.int32)
        stream[0, 0] = int(st2.tokens[0])
        stream[0, 1:4] = serial_toks[:3]
        stream[0, 4:] = garbage_tok
        st2, cache_b, toks, counts = spec.decode(
            cache_b, tables, st2, samp, drafts=jnp.asarray(stream))
        counts = np.asarray(counts)
        kept = [int(t) for row, m in zip(np.asarray(toks)[0], counts[0])
                for t in row[:m]]
        return cache_b, int(counts.sum()), kept

    # Garbage values chosen to differ in embedding magnitude (the int8
    # requant's scale bump depends on absmax — arm A and arm B perturb
    # the touched pages differently before rolling back).
    cache_x, emitted_x, kept_x = spec_dispatch(1)
    cache_y, emitted_y, kept_y = spec_dispatch(CFG.vocab_size - 2)
    assert emitted_x == emitted_y and kept_x == kept_y
    assert 2 <= emitted_x < 8, "stream never partially accepted"
    assert kept_x == serial_toks[:emitted_x]  # sample-and-compare identity

    def real_blocks(arr):
        # Drop the trash block (index TRASH_BLOCK): rejected replay slots
        # mask onto it, and its bytes are garbage by contract.
        a = np.asarray(arr)
        return np.delete(a, TRASH_BLOCK, axis=2 if a.ndim >= 4 else 1)

    np.testing.assert_array_equal(real_blocks(cache_x.k),
                                  real_blocks(cache_y.k))
    np.testing.assert_array_equal(real_blocks(cache_x.v),
                                  real_blocks(cache_y.v))
    if quantized:
        np.testing.assert_array_equal(real_blocks(cache_x.k_scale),
                                      real_blocks(cache_y.k_scale))
        np.testing.assert_array_equal(real_blocks(cache_x.v_scale),
                                      real_blocks(cache_y.v_scale))


def test_rollback_commit_unit_restores_loud_rejection():
    """The int8-specific hazard, pinned surgically (no model numerics in
    the way): a LOUD rejected draft's chained write REQUANTS its page —
    bumping the scale and re-rounding every settled byte — and
    rollback_commit must restore page bytes AND the fp32 scale pair
    exactly, then replay only the accepted write's serial requant."""
    from agentic_traffic_testing_tpu.ops.speculative import (
        rollback_commit,
        snapshot_pages,
        touched_pages,
    )
    from agentic_traffic_testing_tpu.runtime import kv_cache as kvc
    from agentic_traffic_testing_tpu.runtime.kv_cache import KVCache

    rng = np.random.default_rng(9)
    n_layers, kh, nb, bs, hd = 2, 2, 4, 8, 8
    s = 4
    k0 = jnp.asarray(rng.integers(-100, 100, (n_layers, kh, nb, bs, hd)),
                     jnp.int8)
    v0 = jnp.asarray(rng.integers(-100, 100, (n_layers, kh, nb, bs, hd)),
                     jnp.int8)
    ks0 = jnp.asarray(rng.uniform(0.01, 0.05, (n_layers, nb, kh)),
                      jnp.float32)
    vs0 = jnp.asarray(rng.uniform(0.01, 0.05, (n_layers, nb, kh)),
                      jnp.float32)
    clean = KVCache(k0, v0, ks0, vs0)
    tables = jnp.asarray([[1, 2]], jnp.int32)
    positions = jnp.asarray([5], jnp.int32)   # writes at 5..8 span both pages
    k_seq = rng.standard_normal((n_layers, 1, s, kh, hd)).astype(np.float32)
    v_seq = rng.standard_normal((n_layers, 1, s, kh, hd)).astype(np.float32)
    k_seq[:, :, 2] *= 100.0   # the loud REJECTED draft: guaranteed requant
    k_seq, v_seq = jnp.asarray(k_seq), jnp.asarray(v_seq)

    # The round's writes, exactly as verify_step_impl chains them.
    kc, vc, ksc, vsc = clean.k, clean.v, clean.k_scale, clean.v_scale
    for li in range(n_layers):
        for i in range(s):
            kc, ksc = kvc.write_decode_kv_full_quant(
                kc, ksc, jnp.int32(li), k_seq[li, :, i], tables,
                positions + i)
            vc, vsc = kvc.write_decode_kv_full_quant(
                vc, vsc, jnp.int32(li), v_seq[li, :, i], tables,
                positions + i)
    dirty = KVCache(kc, vc, ksc, vsc)
    # The loud write really perturbed settled state (the hazard exists).
    assert not np.array_equal(np.asarray(dirty.k_scale), np.asarray(ks0))

    blks = touched_pages(tables, positions, s, bs)
    snap = snapshot_pages(clean, blks)
    committed = rollback_commit(dirty, snap, blks, k_seq, v_seq, tables,
                                positions, jnp.asarray([1], jnp.int32),
                                capacity=2 * bs)

    # Expectation: the clean pool with ONLY the accepted write (i=0)
    # applied through the same serial requant chain.
    ke, vse_k, ve, vse_v = clean.k, clean.k_scale, clean.v, clean.v_scale
    for li in range(n_layers):
        ke, vse_k = kvc.write_decode_kv_full_quant(
            ke, vse_k, jnp.int32(li), k_seq[li, :, 0], tables, positions)
        ve, vse_v = kvc.write_decode_kv_full_quant(
            ve, vse_v, jnp.int32(li), v_seq[li, :, 0], tables, positions)

    def real(arr, axis):
        # The trash block absorbs the rejected replays' masked writes —
        # garbage by contract, excluded like every masked-write test.
        return np.delete(np.asarray(arr), TRASH_BLOCK, axis=axis)

    np.testing.assert_array_equal(real(committed.k, 2), real(ke, 2))
    np.testing.assert_array_equal(real(committed.v, 2), real(ve, 2))
    np.testing.assert_array_equal(real(committed.k_scale, 1),
                                  real(vse_k, 1))
    np.testing.assert_array_equal(real(committed.v_scale, 1),
                                  real(vse_v, 1))


def test_spec_int8_engine_identity(params):
    """Engine-level int8 x speculation: greedy and seeded output matches
    the non-speculative int8 engine exactly on these fixtures (the
    committed pool is byte-identical by the rollback; the only residual
    caveat is the documented in-round transient-scale visibility, which
    these workloads do not excite)."""
    for samp in (SamplingParams(temperature=0.0, max_tokens=16,
                                ignore_eos=True),
                 SamplingParams(temperature=0.7, seed=11, max_tokens=16,
                                ignore_eos=True)):
        import dataclasses

        want = make_engine(params, kv_cache_dtype="int8").generate(
            REPETITIVE, dataclasses.replace(samp)).generated_ids
        got = make_engine(params, speculation="ngram",
                          kv_cache_dtype="int8").generate(
            REPETITIVE, dataclasses.replace(samp)).generated_ids
        assert got == want


# ---------------------------------------------------------------------------
# speculation=None: the non-speculative paths are untouched
# ---------------------------------------------------------------------------


def test_spec_off_never_touches_spec_code(params, monkeypatch):
    """The default keeps every compiled program byte-identical: with
    speculation off, NO ops/speculative function runs anywhere — neither
    through the runner's jit construction nor the engine's dispatch path
    — and output matches a reference built before the patch."""
    want = make_engine(params).generate(
        REPETITIVE, SamplingParams(max_tokens=12, temperature=0.0,
                                   ignore_eos=True)).generated_ids

    import agentic_traffic_testing_tpu.ops.speculative as spec_mod
    import agentic_traffic_testing_tpu.runtime.runner as runner_mod

    def boom(*a, **kw):
        raise AssertionError("speculative code ran with speculation=None")

    for mod in (spec_mod, runner_mod):
        for name in ("propose_stream", "align_drafts", "accept_counts",
                     "touched_pages", "snapshot_pages", "rollback_commit",
                     "propose_ngram_host"):
            if hasattr(mod, name):
                monkeypatch.setattr(mod, name, boom)
    got = make_engine(params).generate(
        REPETITIVE, SamplingParams(max_tokens=12, temperature=0.0,
                                   ignore_eos=True)).generated_ids
    assert got == want


def test_engine_refuses_mismatched_spec_runner(params):
    """cfg speculation with a non-speculative supplied runner (and the
    reverse) must refuse at build — the spec verify program is baked into
    the runner's jits, and silently serving the other path while
    llm_config_speculation reports the cfg's value is exactly the
    misconfiguration class the fused_kv_write mismatch check refuses."""
    kw = dict(model="tiny", dtype="float32", max_model_len=128,
              block_size=8, num_blocks=96)
    plain = ModelRunner(CFG, params, decode_steps=1)
    with pytest.raises(ValueError, match="spec"):
        LLMEngine(EngineConfig(speculation="ngram", **kw),
                  model_cfg=CFG, runner=plain)
    spec = ModelRunner(CFG, params, decode_steps=1, spec_tokens=3)
    with pytest.raises(ValueError, match="spec"):
        LLMEngine(EngineConfig(**kw), model_cfg=CFG, runner=spec)


def test_pp_runner_refuses_speculation(params):
    """supports_speculation=False must refuse at engine build for a
    caller-supplied non-speculative-capable runner (the pp constructor
    refuses spec_tokens itself; the engine guard covers the cfg side)."""
    class NoSpecRunner(ModelRunner):
        supports_speculation = False

    runner = NoSpecRunner(CFG, params, decode_steps=1)
    with pytest.raises(ValueError, match="speculative"):
        LLMEngine(EngineConfig(model="tiny", dtype="float32",
                               max_model_len=128, block_size=8,
                               num_blocks=96, speculation="ngram"),
                  model_cfg=CFG, runner=runner)


# ---------------------------------------------------------------------------
# multi-query (verify) paged-attention kernels vs oracle
# ---------------------------------------------------------------------------

KERNELS = {"v1": paged_attention_decode, "dma": paged_attention_decode_dma}


@pytest.mark.parametrize("kernel", KERNELS.values(), ids=KERNELS)
@pytest.mark.parametrize(
    "b,s,h,kh,hd,bs,ctx_lens",
    [
        (2, 4, 4, 2, 64, 4, [5, 9]),       # GQA 2:1
        (1, 2, 8, 1, 128, 4, [13]),        # MQA, hd=128
        (3, 3, 4, 4, 64, 8, [1, 8, 17]),   # MHA, boundary lengths
    ],
)
def test_multiquery_kernel_matches_oracle(kernel, b, s, h, kh, hd, bs, ctx_lens):
    rng = np.random.default_rng(11)
    # blocks must cover ctx + s - 1 slots: verify writes draft KV that far
    blocks_per = [-(-(ln + s - 1) // bs) for ln in ctx_lens]
    max_blocks = max(blocks_per) + 1
    num_blocks = 1 + sum(blocks_per) + 1
    q = jnp.asarray(rng.standard_normal((b, s, h, hd)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((kh, num_blocks, bs, hd)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((kh, num_blocks, bs, hd)), jnp.float32)
    bt = np.full((b, max_blocks), TRASH_BLOCK, np.int32)
    nxt = 1
    for i, n in enumerate(blocks_per):
        bt[i, :n] = np.arange(nxt, nxt + n)
        nxt += n
    bt = jnp.asarray(bt)
    cl = jnp.asarray(ctx_lens, jnp.int32)

    got = kernel(q, kp, vp, bt, cl, interpret=True)

    k_all = gather_kv(kp, bt)
    v_all = gather_kv(vp, bt)
    q_pos = (cl - 1)[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
    want = causal_attention(q, k_all, v_all, q_positions=q_pos,
                            kv_valid_len=cl + s - 1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)
