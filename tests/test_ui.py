"""UI↔server contract pins (no JS runtime in CI — structural checks).

The AgentVerse UI is plain-script modules; these tests keep the parts that
must agree with the Python side from drifting: module wiring, element ids,
the SSE event vocabulary, and the example-task catalog.
"""

from __future__ import annotations

import json
import pathlib
import re

REPO = pathlib.Path(__file__).resolve().parent.parent
UI = REPO / "ui" / "agentverse"
ORCH = (REPO / "agentic_traffic_testing_tpu" / "agents" / "agent_a"
        / "orchestrator.py").read_text()

MODULES = ["utils.js", "config.js", "ui-state.js", "streaming.js",
           "renderers.js", "app.js"]


def test_index_loads_all_modules_in_order():
    html = (UI / "index.html").read_text()
    srcs = re.findall(r'<script src="([^"]+)"', html)
    assert srcs == MODULES


def test_all_modules_exist():
    for m in MODULES:
        assert (UI / m).exists(), m


def test_js_element_ids_exist_in_html():
    html = (UI / "index.html").read_text()
    html_ids = set(re.findall(r'id="([^"]+)"', html))
    js = "".join((UI / m).read_text() for m in MODULES)
    for used in set(re.findall(r'\$\("([^"]+)"\)', js)):
        if used.startswith("stage-"):
            continue  # generated per-stage at runtime
        assert used in html_ids, f"JS references #{used}, missing from index.html"


def test_ui_state_covers_orchestrator_event_vocabulary():
    emitted = set(re.findall(r'_emit\(cb,\s*"(\w+)"', ORCH))
    emitted |= {"llm_request", "llm_error"}  # emitted via a variable expression
    ui_state = (UI / "ui-state.js").read_text()
    handled = set(re.findall(r'case "(\w+)":', ui_state))
    missing = emitted - handled
    assert not missing, f"ui-state.js does not handle events: {missing}"


def test_example_tasks_in_sync_with_template():
    tmpl = json.loads((REPO / "agentic_traffic_testing_tpu" / "agents"
                       / "templates" / "agentverse_workflow.json").read_text())
    config_js = (UI / "config.js").read_text()
    for task in tmpl["example_tasks"]:
        assert task["task_id"] in config_js, (
            f"config.js fallback misses example task {task['task_id']}")


def test_streaming_module_handles_result_frame_and_fallback():
    streaming = (UI / "streaming.js").read_text()
    assert '"result"' in streaming or "=== \"result\"" in streaming
    assert "runNonStreaming" in streaming  # non-streaming fallback exists


def test_renderers_use_actual_event_fields():
    renderers = (UI / "renderers.js").read_text()
    # Fields the orchestrator actually emits (not invented ones).
    for field in ("plan_preview", "vertical_round", "result_preview",
                  "overall_score", "expertise", "responsibility"):
        assert field in renderers, f"renderers.js missing server field {field}"


def test_renderers_cover_flow_graph_and_history():
    """Round-2 depth views (parity: reference renderers.js
    renderLlmRequestsGraph / renderIterationHistory): the swim-lane request
    flow and the iteration score chart exist, render into their panels, and
    repaint on the events that can change them."""
    js = (UI / "renderers.js").read_text()
    assert "function renderFlowGraph" in js
    assert "function renderHistory" in js
    for lane in ("Agent A", "Agent B", "LLM backend"):
        assert lane in js, f"flow graph missing lane {lane}"
    # Wired into the per-event repaint map.
    panels = re.search(r"const EVENT_PANELS = \{(.*?)\};", js, re.S).group(1)
    assert "renderFlowGraph" in panels and "renderHistory" in panels
    # Wired into full repaints too.
    render_all = re.search(r"function renderAll\(state\) \{(.*?)\n\}", js, re.S).group(1)
    assert "renderFlowGraph" in render_all and "renderHistory" in render_all
    html = (UI / "index.html").read_text()
    assert 'id="flow"' in html and 'id="history"' in html
