"""The kernel-contract checker (statics/kernelcontract.py).

Seeded-violation fixtures per rule — an illegal int8 (16, 128) tile, a
dropped scratch param (the dma3 `rc_ref` crash class), a
shape-mismatched alias, a parallel-axis write-then-read, a VMEM budget
blowout — plus pragma-suppression and clean-tree negatives, registry
parity both ways, the budget-constant unification, and the
generate-vs-committed docs/kernels.md round trip.

Pure AST work on tmp fixture trees: no jax arrays, no kernels traced —
milliseconds in the default tier (the two constant-unification tests
import ops modules, which pull jax but trace nothing).
"""

import os
import textwrap

import pytest

from agentic_traffic_testing_tpu.statics import kernelcontract
from agentic_traffic_testing_tpu.statics.common import Finding, repo_root
from agentic_traffic_testing_tpu.statics.kernel_registry import (
    INT4_UNPACK_I32_BUDGET_BYTES,
    KERNELS,
    PIPELINE_VMEM_BUDGET_BYTES,
    VMEM_BYTES_PER_CORE,
    Kernel,
    KernelVariant,
)

REPO = repo_root()


def write(tmp_path, relpath: str, body: str) -> str:
    p = tmp_path / relpath
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(body))
    return str(p)


def rules(findings: list[Finding]) -> list[str]:
    return sorted(f.rule for f in findings)


RUNNER = """\
    class Runner:
        def __init__(self):
            self._decode = jax.jit(_impl, donate_argnames=("cache",))

        def decode(self, cache):
            return self._decode(cache)
"""

# The baseline fixture: arity 0+1+1+1 == the 3 kernel params, legal f32
# (32, 128) tiles, "arbitrary" grid — every test below perturbs exactly
# one contract surface.
CLEAN = """\
    def _fix_kernel(x_ref, o_ref, acc_ref):
        acc_ref[...] = x_ref[...]
        o_ref[...] = acc_ref[...]

    def fix_wrapper(x):
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(8,),
            in_specs=[pl.BlockSpec((32, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((32, 128), lambda i: (i, 0)),
            scratch_shapes=[pltpu.VMEM((32, 128), jnp.float32)],
        )
        return pl.pallas_call(
            _fix_kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            compiler_params=CompilerParams(
                dimension_semantics=("arbitrary",)),
        )(x)
"""


def entry(**kw) -> Kernel:
    base = dict(
        name="fix", module="m.py", wrapper="fix_wrapper",
        body="_fix_kernel", grid="(8,)", intent="fixture",
        variants=(KernelVariant("base"),),
    )
    base.update(kw)
    return Kernel(**base)


def check_fixture(tmp_path, source: str, kernel: Kernel) -> list[Finding]:
    mpath = write(tmp_path, "m.py", source)
    rpath = write(tmp_path, "runner.py", RUNNER)
    return kernelcontract.check(
        root=str(tmp_path), registry=(kernel,), paths=[mpath],
        runner_path=rpath, check_doc=False)


# ------------------------------------------------------------- clean tree


def test_fixture_clean(tmp_path):
    assert check_fixture(tmp_path, CLEAN, entry()) == []


def test_repo_tree_clean():
    """Every real ops/pallas/ call site honors its declared contract
    (fixed or reason-pragma'd — zero bare allows) and docs/kernels.md is
    current: the acceptance bar for every future kernel edit."""
    assert kernelcontract.check(REPO) == []


# ----------------------------------------------------------------- tiling


def test_illegal_int8_tile_fires(tmp_path):
    """The acceptance seed: a (16, 128) tile on an int8 operand is below
    the (32, 128) int8 minimum — the 8-bit tiling-legality bug class."""
    src = CLEAN.replace("(32, 128), lambda i: (i, 0))],",
                        "(16, 128), lambda i: (i, 0))],")
    kern = entry(variants=(KernelVariant("int8", dtypes={"x": "int8"}),))
    fs = check_fixture(tmp_path, src, kern)
    assert rules(fs) == ["kernel-tile"]
    assert "int8 minimum 32" in fs[0].message


def test_bf16_sublane_minimum(tmp_path):
    """(8, 128) is legal f32 but sub-minimum bf16 (16, 128)."""
    src = CLEAN.replace("(32, 128)", "(8, 128)").replace(
        "jnp.float32", "x.dtype")
    assert check_fixture(
        tmp_path, src,
        entry(variants=(KernelVariant("f32", dtypes={"x": "f32"}),))) == []
    fs = check_fixture(
        tmp_path, src,
        entry(variants=(KernelVariant("bf16", dtypes={"x": "bf16"}),)))
    assert "kernel-tile" in rules(fs)


def test_unaligned_lane_dim_fires(tmp_path):
    src = CLEAN.replace("(32, 128), lambda i: (i, 0))],",
                        "(32, 100), lambda i: (i, 0))],")
    fs = check_fixture(tmp_path, src, entry())
    assert rules(fs) == ["kernel-tile"]
    assert "multiple of 128" in fs[0].message


def test_full_axis_symbol_exempt(tmp_path):
    """A sub-sublane dim spelled as a registry full-axis symbol is legal
    (the block spans the operand's whole axis; Mosaic pads once)."""
    src = CLEAN.replace(
        "def fix_wrapper(x):", "def fix_wrapper(x):\n        rows = 4")
    src = src.replace("in_specs=[pl.BlockSpec((32, 128), lambda i: (i, 0))]",
                      "in_specs=[pl.BlockSpec((rows, 128), lambda i: (i, 0))]")
    fs = check_fixture(tmp_path, src, entry(full_axis=frozenset({"rows"})))
    assert fs == []
    assert "kernel-tile" in rules(check_fixture(tmp_path, src, entry()))


def test_tile_pragma_suppresses(tmp_path):
    src = CLEAN.replace(
        "in_specs=[pl.BlockSpec((32, 128), lambda i: (i, 0))],",
        "in_specs=[pl.BlockSpec((16, 128), lambda i: (i, 0))],"
        "  # statics: allow-kernel-tile(deliberate sub-tile fixture)")
    kern = entry(variants=(KernelVariant("int8", dtypes={"x": "int8"}),))
    assert check_fixture(tmp_path, src, kern) == []


def test_out_spec_literal_dtype_checked(tmp_path):
    """An out_shape dtyped by a LITERAL jnp dtype is tile-checked under
    that dtype, not the kernel's default — an illegal int8 out tile
    fires even when the entry's default_dtype would make it legal."""
    src = CLEAN.replace("jax.ShapeDtypeStruct(x.shape, x.dtype)",
                        "jax.ShapeDtypeStruct((64, 128), jnp.int8)")
    src = src.replace("out_specs=pl.BlockSpec((32, 128), lambda i: (i, 0)),",
                      "out_specs=pl.BlockSpec((16, 128), lambda i: (i, 0)),")
    fs = check_fixture(tmp_path, src, entry())
    assert "kernel-tile" in rules(fs)
    assert any("int8 minimum 32" in f.message for f in fs)


def test_lane_dim_of_one_is_exempt(tmp_path):
    """A trailing dim of exactly 1 is a replicated vector in either
    position — the documented exemption covers the lane dim too."""
    src = CLEAN.replace("pltpu.VMEM((32, 128), jnp.float32)",
                        "pltpu.VMEM((8, 1), jnp.float32)")
    assert check_fixture(tmp_path, src, entry()) == []


# ------------------------------------------------------------------ arity


def test_dropped_scratch_param_fires(tmp_path):
    """The acceptance seed (the PR-1 dma3 rc_ref crash, at lint time):
    the spec lists stop providing a ref the body still consumes."""
    src = CLEAN.replace(
        "scratch_shapes=[pltpu.VMEM((32, 128), jnp.float32)],",
        "scratch_shapes=[],")
    fs = check_fixture(tmp_path, src, entry())
    assert rules(fs) == ["kernel-arity"]
    assert "consumes 3 refs but the specs provide 2" in fs[0].message


def test_arity_counts_flag_gated_next_refs(tmp_path):
    """*refs bodies are counted through their flag-gated next(it)
    prologue, so a variant's ref count follows its configuration."""
    src = """\
        def _fix_kernel(*refs, quantized):
            it = iter(refs)
            x_ref, o_ref = next(it), next(it)
            if quantized:
                s_ref = next(it)
            acc_ref = next(it)

        def fix_wrapper(x, quantized):
            in_specs = [pl.BlockSpec((32, 128), lambda i: (i, 0))]
            if quantized:
                in_specs += [pl.BlockSpec((32, 128), lambda i: (i, 0))]
            grid_spec = pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=0,
                grid=(8,),
                in_specs=in_specs,
                out_specs=pl.BlockSpec((32, 128), lambda i: (i, 0)),
                scratch_shapes=[pltpu.VMEM((32, 128), jnp.float32)],
            )
            return pl.pallas_call(
                _fix_kernel,
                grid_spec=grid_spec,
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                compiler_params=CompilerParams(
                    dimension_semantics=("arbitrary",)),
            )(x)
    """
    kern = entry(variants=(
        KernelVariant("base", flags={"quantized": False}),
        KernelVariant("quant", flags={"quantized": True}),
    ))
    assert check_fixture(tmp_path, src, kern) == []
    # Dropping the flag-gated spec breaks ONLY the quantized variant.
    broken = src.replace("            if quantized:\n"
                         "                in_specs += "
                         "[pl.BlockSpec((32, 128), lambda i: (i, 0))]\n",
                         "")
    fs = check_fixture(tmp_path, broken, kern)
    assert rules(fs) == ["kernel-arity"]
    assert "[quant]" in fs[0].message


# --------------------------------------------------------------- aliasing


ALIAS = """\
    def _fix_kernel(x_ref, y_ref, o_ref):
        o_ref[...] = x_ref[...]

    def fix_wrapper(x, y):
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(8,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY),
                      pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            scratch_shapes=[],
        )
        return pl.pallas_call(
            _fix_kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct({out_of}.shape, {out_of}.dtype),
            input_output_aliases={{0: 0}},
            compiler_params=CompilerParams(
                dimension_semantics=("arbitrary",)),
        )(x, y)
"""


def test_alias_agreeing_pair_clean(tmp_path):
    src = ALIAS.format(out_of="x")
    kern = entry(aliased=("x",), donated_as=("cache",))
    assert check_fixture(tmp_path, src, kern) == []


def test_shape_mismatched_alias_fires(tmp_path):
    """The acceptance seed: aliasing input x onto an output whose
    ShapeDtypeStruct is built from a DIFFERENT array."""
    src = ALIAS.format(out_of="y")
    kern = entry(aliased=("x",), donated_as=("cache",))
    fs = check_fixture(tmp_path, src, kern)
    assert rules(fs) == ["kernel-alias", "kernel-alias"]  # shape + dtype
    assert "output shaped from `y`" in fs[0].message


def test_dtype_mismatched_alias_fires(tmp_path):
    """Both halves of the alias contract are enforced: an output shaped
    from the aliased array but dtyped from a literal (or another array)
    fails — the dtype half cannot be verified as agreeing."""
    src = ALIAS.format(out_of="x").replace("x.dtype", "jnp.bfloat16")
    kern = entry(aliased=("x",), donated_as=("cache",))
    fs = check_fixture(tmp_path, src, kern)
    assert rules(fs) == ["kernel-alias"]
    assert "dtyped from" in fs[0].message


def test_two_pallas_calls_in_one_wrapper_refused(tmp_path):
    """A second pl.pallas_call in a registered wrapper is a loud
    kernel-extract finding, never a silently-unchecked site."""
    body = CLEAN.replace(
        "        )(x)\n",
        "        )(x)\n"
        "        return pl.pallas_call(\n"
        "            _fix_kernel,\n"
        "            grid_spec=grid_spec,\n"
        "            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),\n"
        "            compiler_params=CompilerParams(\n"
        "                dimension_semantics=(\"arbitrary\",)),\n"
        "        )(x)\n")
    fs = check_fixture(tmp_path, body, entry())
    assert "kernel-extract" in rules(fs)
    assert "exactly one" in " ".join(f.message for f in fs)


def test_undeclared_aliased_buffer_fires(tmp_path):
    src = ALIAS.format(out_of="x")
    kern = entry(aliased=("z",), donated_as=("cache",))
    fs = check_fixture(tmp_path, src, kern)
    assert rules(fs) == ["kernel-alias"]
    assert "not declared in the kernel registry" in fs[0].message


def test_dead_aliased_declaration_fires(tmp_path):
    """The dead-row direction: a registry `aliased` declaration with no
    variant emitting input_output_aliases means the fused in-place write
    silently stopped existing."""
    fs = check_fixture(tmp_path, CLEAN,
                       entry(aliased=("x",), donated_as=("cache",)))
    assert rules(fs) == ["kernel-alias"]
    assert "no variant's call site emits" in fs[0].message


def test_undonated_aliased_pool_fires(tmp_path):
    """The donation cross-check: an aliased fused-write pool must travel
    under a runner donate_argnames name, or the donation checker's
    engine walk cannot see post-dispatch reads of it."""
    src = ALIAS.format(out_of="x")
    kern = entry(aliased=("x",), donated_as=("not_donated_anywhere",))
    fs = check_fixture(tmp_path, src, kern)
    assert rules(fs) == ["kernel-alias"]
    assert "donate_argnames" in fs[0].message


# ---------------------------------------------------------- grid semantics


def test_parallel_write_then_read_fires(tmp_path):
    """The acceptance seed: a body that stores-then-loads a ref across
    grid steps under a "parallel" axis with no registry justification —
    the exact shape that forced ragged's fused grid to "arbitrary"."""
    src = CLEAN.replace('("arbitrary",)', '("parallel",)')
    fs = check_fixture(tmp_path, src, entry())
    assert rules(fs) == ["kernel-grid"]
    assert "acc_ref" in fs[0].message and "parallel" in fs[0].message


def test_parallel_with_registry_reason_clean(tmp_path):
    src = CLEAN.replace('("arbitrary",)', '("parallel",)')
    kern = entry(parallel_reason="each program re-initializes its scratch")
    assert check_fixture(tmp_path, src, kern) == []


def test_parallel_pure_map_needs_no_reason(tmp_path):
    """No cross-step ref state -> "parallel" is trivially safe."""
    src = CLEAN.replace('("arbitrary",)', '("parallel",)')
    src = src.replace("        acc_ref[...] = x_ref[...]\n"
                      "        o_ref[...] = acc_ref[...]\n",
                      "        o_ref[...] = x_ref[...]\n")
    assert check_fixture(tmp_path, src, entry()) == []


def test_semantics_grid_rank_mismatch_fires(tmp_path):
    src = CLEAN.replace('("arbitrary",)', '("arbitrary", "arbitrary")')
    fs = check_fixture(tmp_path, src, entry())
    assert rules(fs) == ["kernel-grid"]
    assert "rank-1 grid" in fs[0].message


# ------------------------------------------------------------- VMEM budget


def test_budget_blowout_fires(tmp_path):
    """A 32 MiB f32 scratch blows every generation's 16 MiB budget."""
    src = CLEAN.replace("pltpu.VMEM((32, 128), jnp.float32)",
                        "pltpu.VMEM((8192, 1024), jnp.float32)")
    fs = check_fixture(tmp_path, src, entry())
    assert rules(fs) == ["kernel-vmem"]
    assert "exceeds the VMEM budget" in fs[0].message


def test_budget_counts_double_buffered_blocks(tmp_path):
    """Pipelined blocks cost 2x (Mosaic double-buffers them): two 6 MiB
    bf16 blocks would fit single-buffered (12 MiB) but the ledger's
    double-buffer factor takes them to 24 MiB > 16 MiB."""
    src = CLEAN.replace("(32, 128), lambda i: (i, 0))],",
                        "(24576, 128), lambda i: (i, 0))],")
    src = src.replace("out_specs=pl.BlockSpec((32, 128), lambda i: (i, 0)),",
                      "out_specs=pl.BlockSpec((24576, 128), lambda i: (i, 0)),")
    fs = check_fixture(tmp_path, src, entry())
    assert rules(fs) == ["kernel-vmem"]


def test_budget_extra_vmem_expression(tmp_path):
    """The declared scoped extra (the int4 i32 unpack intermediates)
    rides the ledger, evaluated in the variant env."""
    kern = entry(extra_vmem="17 * 2**20")
    fs = check_fixture(tmp_path, CLEAN, kern)
    assert rules(fs) == ["kernel-vmem"]


# --------------------------------------------------- loud extract failures


def test_unresolvable_block_shape_fires(tmp_path):
    """A shape the interpreter cannot evaluate is a kernel-extract
    finding, never a silent exemption from the tile/vmem rules."""
    src = CLEAN.replace(
        "def fix_wrapper(x):",
        "def fix_wrapper(x):\n        blk = choose_block(x)")
    src = src.replace("pl.BlockSpec((32, 128), lambda i: (i, 0))],",
                      "pl.BlockSpec(blk, lambda i: (i, 0))],")
    fs = check_fixture(tmp_path, src, entry())
    assert "kernel-extract" in rules(fs)
    assert any("in_specs[0]" in f.message for f in fs)


def test_unresolvable_vmem_shape_fires(tmp_path):
    src = CLEAN.replace(
        "def fix_wrapper(x):",
        "def fix_wrapper(x):\n        blk = choose_block(x)")
    src = src.replace("pltpu.VMEM((32, 128), jnp.float32)",
                      "pltpu.VMEM(blk, jnp.float32)")
    fs = check_fixture(tmp_path, src, entry())
    assert "kernel-extract" in rules(fs)
    assert any("scratch_shapes[0]" in f.message for f in fs)


def test_unresolvable_aliases_fires(tmp_path):
    """An alias map the interpreter cannot evaluate disables the whole
    alias contract — that must be a finding, not a silent pass."""
    src = ALIAS.format(out_of="x").replace(
        "input_output_aliases={0: 0},",
        "input_output_aliases=_alias_map(x),")
    kern = entry(aliased=("x",), donated_as=("cache",))
    fs = check_fixture(tmp_path, src, kern)
    assert "kernel-extract" in rules(fs)
    assert any("input_output_aliases" in f.message for f in fs)


# ------------------------------------------------------------------ parity


def test_unregistered_site_fires(tmp_path):
    fs = check_fixture(tmp_path, CLEAN,
                       entry(wrapper="other_wrapper_name"))
    assert rules(fs) == ["kernel-registry-dead", "kernel-unregistered"]


def test_registry_dead_entry_fires(tmp_path):
    fs = check_fixture(tmp_path, CLEAN, entry(module="nonesuch.py"))
    assert "kernel-registry-dead" in rules(fs)


# ----------------------------------------------- budget-constant unification


def test_autotune_budget_is_registry_owned():
    from agentic_traffic_testing_tpu.ops.pallas import autotune

    assert autotune._VMEM_BUDGET_BYTES == PIPELINE_VMEM_BUDGET_BYTES
    assert PIPELINE_VMEM_BUDGET_BYTES == 12 * 2**20  # value unchanged
    assert PIPELINE_VMEM_BUDGET_BYTES < min(VMEM_BYTES_PER_CORE.values())


def test_int4_budget_is_registry_owned():
    from agentic_traffic_testing_tpu.ops.pallas import int4_matmul

    assert int4_matmul.VMEM_I32_BUDGET == INT4_UNPACK_I32_BUDGET_BYTES
    assert INT4_UNPACK_I32_BUDGET_BYTES == 8_000_000  # value unchanged


# ------------------------------------------------------------------- docs


def test_kernels_doc_round_trip():
    """docs/kernels.md regenerates byte-identical to the committed copy."""
    with open(os.path.join(REPO, "docs", "kernels.md"),
              encoding="utf-8") as f:
        committed = f.read()
    assert committed == kernelcontract.render(REPO)


def test_kernels_doc_drift_fires(tmp_path):
    doc = tmp_path / "kernels.md"
    doc.write_text(kernelcontract.render(REPO) + "\nEDITED\n")
    fs = [f for f in kernelcontract.check(REPO, doc_path=str(doc))
          if f.rule == "kernel-docs-stale"]
    assert len(fs) == 1 and "--write-docs" in fs[0].message
    doc.write_text(kernelcontract.render(REPO))
    assert kernelcontract.check(REPO, doc_path=str(doc)) == []


def test_doc_rows_cover_every_registry_variant():
    doc = kernelcontract.render(REPO)
    for kern in KERNELS:
        assert f"## `{kern.name}`" in doc
        for variant in kern.variants:
            assert f"| `{variant.name}` |" in doc


def test_registry_entries_have_grid_semantics_justifications():
    """Every in-tree entry whose kernels declare "parallel" axes with
    carried state documents WHY — the registry carries the justification
    the checker enforces."""
    for kern in KERNELS:
        if kern.name in ("kv_write",):  # all-"arbitrary" grids
            continue
        assert kern.parallel_reason, kern.name


@pytest.mark.parametrize("kern", KERNELS, ids=lambda k: k.name)
def test_every_registry_variant_extracts(kern):
    """The abstract interpreter resolves every declared variant of every
    real call site (no silent kernel-extract degradation)."""
    from agentic_traffic_testing_tpu.statics.common import SourceFile

    src = SourceFile(os.path.join(REPO, kern.module), REPO)
    for variant in kern.variants:
        facts = kernelcontract.extract(src, kern, variant)
        assert facts.grid is not None
        assert facts.semantics is not None
        assert facts.num_prefetch is not None
        total = kernelcontract.step_vmem_bytes(kern, variant, facts)
        assert total is not None
