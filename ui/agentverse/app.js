/* AgentVerse live UI entrypoint (parity: reference ui/agentverse/app.js).
 * Wires the form to streaming.js, folds events through RunState
 * (ui-state.js) and repaints via renderers.js. Modules are plain scripts
 * loaded in order by index.html — same structure as the reference UI. */

let state = new RunState();

function endpointBase() {
  const v = $("endpoint").value.trim();
  return v ? v.replace(/\/+$/, "") : AGENTVERSE_DEFAULT_ENDPOINT;
}

function setStatus(cls, text) {
  const el = $("status");
  el.className = `status ${cls}`;
  el.textContent = text;
}

function iterTabHandler(ev) {
  const btn = ev.target.closest(".iter-tab");
  if (!btn) return;
  state.currentIteration = Number(btn.dataset.iter);
  renderAll(state);
}

async function run() {
  const task = $("task").value.trim();
  if (!task) { setStatus("error", "enter a task"); return; }
  state = new RunState();
  renderAll(state);
  setStatus("running", "running…");
  $("runBtn").disabled = true;

  const body = {
    task,
    structure: $("structure").value,
    num_experts: Number($("agents").value || WORKFLOW_DEFAULTS.agent_count),
    max_iterations: Number($("iters").value || WORKFLOW_DEFAULTS.max_iterations),
  };

  try {
    const { streamed, final } = await runWorkflow(
      `${endpointBase()}/agentverse`, body,
      (ev) => { state.apply(ev); renderFor(state, ev.event); });
    if (final) {
      // Streamed runs already folded every event; only take the summary
      // fields from the result frame. The non-streaming path folds the
      // whole response (it saw no events).
      if (streamed) state.applyResultSummary(final);
      else state.applyFinalResponse(final);
    }
    renderAll(state);
    setStatus(state.error ? "error" : "done",
              state.error ? "workflow error" :
              streamed ? "done (streamed)" : "done (non-streaming)");
    if (state.taskId) $("taskId").value = state.taskId;
  } catch (err) {
    setStatus("error", String(err));
  } finally {
    $("runBtn").disabled = false;
  }
}

/* Reload a persisted run by task id (GET /agentverse/<id>) — the server
 * keeps every workflow at logs/agentverse/<task_id>.json. */
async function loadRun() {
  const id = $("taskId").value.trim();
  if (!id) return;
  setStatus("running", `loading ${id}…`);
  try {
    const resp = await fetchRun(endpointBase(), id);
    state = new RunState();
    state.applyFinalResponse(resp);
    renderAll(state);
    setStatus("done", `loaded ${id}`);
  } catch (err) {
    setStatus("error", `load failed: ${err}`);
  }
}

/* Prefer live examples from the served template; fall back to config.js. */
async function loadExamples() {
  const sel = $("example");
  let tasks = EXAMPLE_TASKS;
  try {
    const resp = await fetch("../templates/agentverse_workflow.json");
    if (resp.ok) {
      const tmpl = await resp.json();
      if (tmpl.example_tasks?.length) tasks = tmpl.example_tasks;
    }
  } catch { /* static fallback */ }
  for (const t of tasks) {
    const opt = document.createElement("option");
    opt.value = t.task;
    opt.textContent = t.task_id;
    sel.appendChild(opt);
  }
}

function init() {
  loadExamples();
  $("example").addEventListener("change", (e) => {
    if (e.target.value) $("task").value = e.target.value;
  });
  $("structure").value = WORKFLOW_DEFAULTS.structure;
  $("agents").value = WORKFLOW_DEFAULTS.agent_count;
  $("iters").value = WORKFLOW_DEFAULTS.max_iterations;
  $("runBtn").addEventListener("click", run);
  $("loadBtn").addEventListener("click", loadRun);
  $("iterations").addEventListener("click", iterTabHandler);
  $("task").addEventListener("keydown", (e) => {
    if (e.key === "Enter" && (e.metaKey || e.ctrlKey)) run();
  });
  renderAll(state);
}

document.addEventListener("DOMContentLoaded", init);
