/* AgentVerse live client: POST /agentverse with stream:true, parse the SSE
 * body incrementally (fetch + ReadableStream — EventSource can't POST), and
 * render stages/events/calls. Falls back to the non-streaming JSON response
 * when streaming fails (parity with reference streaming.js fallback). */

const $ = (id) => document.getElementById(id);
const STAGES = ["recruitment", "decision", "execution", "evaluation"];

function endpointBase() {
  const v = $("endpoint").value.trim();
  return v ? v.replace(/\/+$/, "") : `http://${location.hostname}:8101`;
}

function setStatus(cls, text) {
  const el = $("status");
  el.className = `status ${cls}`;
  el.textContent = text;
}

function resetPanels() {
  $("stages").innerHTML = STAGES.map(
    (s) => `<div class="stage" id="stage-${s}"><h4>${s}</h4>
            <div class="detail">waiting…</div></div>`).join("");
  $("events").innerHTML = "";
  $("calls").querySelector("tbody").innerHTML = "";
  $("final").textContent = "…";
}

function logEvent(name, payload) {
  const div = document.createElement("div");
  const brief = JSON.stringify(payload).slice(0, 220);
  div.innerHTML = `<span class="evt">${name}</span> ${brief}`;
  $("events").prepend(div);
}

function onEvent(ev) {
  const name = ev.event;
  logEvent(name, ev);
  if (name === "stage_start") {
    const el = $(`stage-${ev.stage}`);
    if (el) { el.classList.add("active");
              el.querySelector(".detail").textContent = "running…"; }
  } else if (name === "stage_complete") {
    const el = $(`stage-${ev.stage}`);
    if (el) {
      el.classList.remove("active");
      el.classList.add("done");
      const d = {...ev}; delete d.event; delete d.stage;
      el.querySelector(".detail").textContent =
        Object.entries(d).map(([k, v]) =>
          `${k}: ${typeof v === "string" ? v.slice(0, 120) : JSON.stringify(v)}`
        ).join("\n");
    }
  } else if (name === "llm_request" || name === "llm_error") {
    const tr = document.createElement("tr");
    tr.innerHTML = `<td>${ev.stage ?? ""}</td><td>${ev.iteration ?? ""}</td>
      <td>${ev.latency_ms ?? ""}</td><td>${ev.prompt_tokens ?? ""}</td>
      <td>${ev.completion_tokens ?? ""}</td>
      <td>${ev.error ? "ERR" : ev.status}</td>`;
    $("calls").querySelector("tbody").appendChild(tr);
  } else if (name === "iteration_start") {
    STAGES.forEach((s) => $(`stage-${s}`)?.classList.remove("done"));
  } else if (name === "result") {
    $("final").textContent = ev.final_output || ev.error || "(no output)";
    setStatus(ev.error ? "error" : "done", ev.error ? "error" : "done");
  } else if (name === "workflow_error" || name === "error") {
    setStatus("error", "error");
  }
}

async function runStreaming(task) {
  const resp = await fetch(`${endpointBase()}/agentverse`, {
    method: "POST",
    headers: {"Content-Type": "application/json",
              "Accept": "text/event-stream"},
    body: JSON.stringify({task, stream: true,
                          structure: $("structure").value}),
  });
  if (!resp.ok || !resp.body) throw new Error(`http ${resp.status}`);
  const reader = resp.body.getReader();
  const decoder = new TextDecoder();
  let buf = "";
  for (;;) {
    const {done, value} = await reader.read();
    if (done) break;
    buf += decoder.decode(value, {stream: true});
    let idx;
    while ((idx = buf.indexOf("\n\n")) >= 0) {
      const chunk = buf.slice(0, idx);
      buf = buf.slice(idx + 2);
      const dataLine = chunk.split("\n").find((l) => l.startsWith("data: "));
      if (dataLine) {
        try { onEvent(JSON.parse(dataLine.slice(6))); } catch { /* partial */ }
      }
    }
  }
}

async function runFallback(task) {
  logEvent("info", {note: "streaming unavailable, falling back to JSON"});
  const resp = await fetch(`${endpointBase()}/agentverse`, {
    method: "POST",
    headers: {"Content-Type": "application/json"},
    body: JSON.stringify({task, structure: $("structure").value}),
  });
  const data = await resp.json();
  (data.llm_calls || []).forEach((c) => onEvent({event: "llm_request", ...c}));
  onEvent({event: "result", ...data});
}

async function run() {
  const task = $("task").value.trim();
  if (!task) return;
  $("runBtn").disabled = true;
  resetPanels();
  setStatus("running", "running");
  try {
    await runStreaming(task);
  } catch (err) {
    try { await runFallback(task); }
    catch (err2) {
      setStatus("error", "error");
      logEvent("error", {error: String(err2)});
    }
  } finally {
    $("runBtn").disabled = false;
  }
}

async function loadExamples() {
  try {
    const resp = await fetch("../templates/agentverse_workflow.json");
    const tmpl = await resp.json();
    for (const t of tmpl.example_tasks || []) {
      const opt = document.createElement("option");
      opt.value = t.task;
      opt.textContent = t.task_id;
      $("example").appendChild(opt);
    }
  } catch { /* UI works without examples */ }
}

$("runBtn").addEventListener("click", run);
$("task").addEventListener("keydown", (e) => { if (e.key === "Enter") run(); });
$("example").addEventListener("change", (e) => {
  if (e.target.value) $("task").value = e.target.value;
});
loadExamples();
