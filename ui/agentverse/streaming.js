/* SSE client (parity: reference ui/agentverse/streaming.js).
 *
 * The orchestrator streams `event: <name>\ndata: <json>\n\n` frames over a
 * POST response, so EventSource (GET-only) can't be used — we parse the
 * fetch ReadableStream incrementally. If streaming is unavailable (proxy
 * buffering, older server), runWorkflow falls back to one non-streaming
 * POST and folds the final JSON through RunState.applyFinalResponse. */

async function streamWorkflow(url, body, onEvent) {
  const resp = await fetch(url, {
    method: "POST",
    headers: { "Content-Type": "application/json", Accept: "text/event-stream" },
    body: JSON.stringify({ ...body, stream: true }),
  });
  if (!resp.ok) throw new Error(`HTTP ${resp.status}`);
  const ctype = resp.headers.get("Content-Type") || "";
  if (!ctype.includes("text/event-stream")) {
    // Server answered with a plain JSON body — treat as non-streaming.
    return { streamed: false, final: await resp.json() };
  }

  const reader = resp.body.getReader();
  const decoder = new TextDecoder();
  let buf = "";
  let finalPayload = null;

  const dispatch = (frame) => {
    let event = "message";
    const dataLines = [];
    for (const line of frame.split("\n")) {
      if (line.startsWith("event:")) event = line.slice(6).trim();
      else if (line.startsWith("data:")) dataLines.push(line.slice(5).trim());
    }
    if (!dataLines.length) return;
    let payload;
    try {
      payload = JSON.parse(dataLines.join("\n"));
    } catch {
      payload = { raw: dataLines.join("\n") };
    }
    if (event === "result") finalPayload = payload;
    else {
      // A render bug on one event must not abort the stream (that would
      // trigger the fallback re-POST and re-run the whole workflow).
      try { onEvent({ event, ...payload }); }
      catch (err) { console.error("event handler failed:", err, payload); }
    }
  };

  for (;;) {
    const { value, done } = await reader.read();
    if (done) break;
    buf += decoder.decode(value, { stream: true });
    let idx;
    while ((idx = buf.indexOf("\n\n")) >= 0) {
      const frame = buf.slice(0, idx);
      buf = buf.slice(idx + 2);
      if (frame.trim()) dispatch(frame);
    }
  }
  if (buf.trim()) dispatch(buf);
  return { streamed: true, final: finalPayload };
}

async function runNonStreaming(url, body) {
  const resp = await fetch(url, {
    method: "POST",
    headers: { "Content-Type": "application/json" },
    body: JSON.stringify({ ...body, stream: false }),
  });
  // A failed workflow returns HTTP 500 *with* the full partial state
  // (iterations, llm_calls, error) — render it rather than discarding.
  try {
    return await resp.json();
  } catch {
    throw new Error(`HTTP ${resp.status}`);
  }
}

/* Try streaming; on transport failure fall back to the blocking request.
 * Returns {streamed, final}; events (streaming mode only) go to onEvent. */
async function runWorkflow(url, body, onEvent) {
  try {
    return await streamWorkflow(url, body, onEvent);
  } catch (err) {
    console.warn("SSE failed, falling back to non-streaming:", err);
    const final = await runNonStreaming(url, body);
    return { streamed: false, final };
  }
}

async function fetchRun(base, taskId) {
  const resp = await fetch(`${base}/agentverse/${encodeURIComponent(taskId)}`);
  if (!resp.ok) throw new Error(`HTTP ${resp.status}`);
  return resp.json();
}
