/* Run-state store (parity: reference ui/agentverse/ui-state.js).
 * One RunState per workflow run; apply(event) folds the SSE stream into a
 * render-ready structure: per-iteration stages, discussion transcript,
 * execution results, the LLM call ledger and running totals. */

class RunState {
  constructor() {
    this.events = [];            // raw event log (latest first, capped)
    this.iterations = new Map(); // iter -> {stages: Map, discussion: [], vertical: [], executions: []}
    this.calls = [];             // llm_request / llm_error records
    this.totals = {
      calls: 0, errors: 0, prompt_tokens: 0, completion_tokens: 0,
      latency_ms: 0, cost_usd: 0,
    };
    this.currentIteration = 1;
    this.taskId = null;
    this.finalOutput = null;
    this.error = null;
    this.done = false;
    this.scores = [];            // evaluation score per iteration
  }

  _iter(n) {
    const k = n ?? this.currentIteration;
    if (!this.iterations.has(k)) {
      this.iterations.set(k, {
        stages: new Map(), discussion: [], vertical: [], executions: [],
      });
    }
    return this.iterations.get(k);
  }

  apply(ev) {
    this.events.unshift({ at: clockNow(), ...ev });
    if (this.events.length > 400) this.events.pop();
    const name = ev.event;
    const it = ev.iteration ?? this.currentIteration;

    switch (name) {
      case "iteration_start":
        this.currentIteration = ev.iteration ?? this.currentIteration;
        this._iter(this.currentIteration);
        break;
      case "stage_start":
        this._iter(it).stages.set(ev.stage, { status: "running", detail: ev });
        break;
      case "stage_complete": {
        const d = { ...ev };
        delete d.event;
        this._iter(it).stages.set(ev.stage, { status: "done", detail: d });
        if (ev.stage === "evaluation" && ev.overall_score != null) {
          this.scores.push({ iteration: it, score: ev.overall_score });
        }
        break;
      }
      case "discussion_round":
        this._iter(it).discussion.push(ev);
        break;
      case "vertical_iteration":
        this._iter(it).vertical.push(ev);
        break;
      case "execution_result":
        this._iter(it).executions.push(ev);
        break;
      case "llm_request":
      case "llm_error": {
        this.calls.push(ev);
        this.totals.calls += 1;
        if (name === "llm_error" || ev.error) this.totals.errors += 1;
        this.totals.prompt_tokens += Number(ev.prompt_tokens || 0);
        this.totals.completion_tokens += Number(ev.completion_tokens || 0);
        this.totals.latency_ms += Number(ev.latency_ms || 0);
        if (ev.cost_estimate_usd != null) {
          this.totals.cost_usd += Number(ev.cost_estimate_usd);
        }
        break;
      }
      case "iteration_complete":
        break;
      case "complete":
        this.done = true;
        this.taskId = ev.task_id ?? this.taskId;
        break;
      case "workflow_error":
      case "error":
        this.error = ev.error ?? "unknown error";
        break;
    }
  }

  /* Take only the summary fields from the final result frame of a streamed
   * run — every per-call/per-stage record was already folded live, so
   * re-applying resp.llm_calls here would double-count. */
  applyResultSummary(resp) {
    this.taskId = resp.task_id ?? this.taskId;
    this.finalOutput = resp.final_output || this.finalOutput;
    if (resp.error) this.error = resp.error;
    if (resp.aggregates?.cost_estimate_usd != null) {
      this.totals.cost_usd = resp.aggregates.cost_estimate_usd;
    }
    this.done = true;
  }

  /* Fold a non-streaming /agentverse JSON response (AgentVerseState
   * .to_response shape) into the same state — the fallback path when SSE is
   * unavailable (reference streaming.js non-streaming mode). */
  applyFinalResponse(resp) {
    this.taskId = resp.task_id ?? this.taskId;
    this.finalOutput = resp.final_output || null;
    if (resp.error) this.error = resp.error;
    for (const r of resp.llm_calls ?? []) {
      this.apply({ event: r.error ? "llm_error" : "llm_request", ...r });
    }
    for (const itn of resp.iterations ?? []) {
      const n = itn.iteration ?? 0;   // orchestrator iterations are 0-based
      this._iter(n).stages.set("evaluation", { status: "done", detail: itn });
      if (itn.overall_score != null) {
        this.scores.push({ iteration: n, score: itn.overall_score });
      }
    }
    const keys = [...this.iterations.keys()];
    const first = keys.length ? Math.min(...keys) : 0;
    if (resp.experts?.length) {
      this._iter(first).stages.set("recruitment", {
        status: "done", detail: { experts: resp.experts },
      });
    }
    this.currentIteration = keys.length ? Math.max(...keys) : first;
    if (resp.aggregates) {
      this.totals.cost_usd = resp.aggregates.cost_estimate_usd ?? this.totals.cost_usd;
    }
    this.done = true;
  }
}
