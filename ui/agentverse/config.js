/* Static UI config (parity: reference ui/agentverse/config.js).
 * EXAMPLE_TASKS mirrors agents/templates/agentverse_workflow.json
 * example_tasks — the same set the experiment runner uses, so UI runs and
 * batch runs exercise identical workloads. */

const AGENTVERSE_DEFAULT_ENDPOINT = `http://${location.hostname}:8101`;

const EXAMPLE_TASKS = [
  {
    task_id: "plan-city-network",
    task: "Design a monitoring plan for a mid-size city's public WiFi network: what to measure, where to place probes, and how to detect degradations early.",
  },
  {
    task_id: "compare-storage",
    task: "Compare three approaches for storing time-series metrics at 1M points/second (columnar files, purpose-built TSDB, object storage with index) and recommend one with justification.",
  },
  {
    task_id: "incident-runbook",
    task: "Write an incident runbook for elevated p95 latency in a microservice behind a load balancer, covering triage steps, likely causes, and rollback criteria.",
  },
  {
    task_id: "summarize-tradeoffs",
    task: "Explain the trade-offs between request-level batching and token-level continuous batching for LLM serving, and when each wins.",
  },
  {
    task_id: "capacity-estimate",
    task: "Estimate the KV-cache memory needed to serve 32 concurrent chats at 8k context on an 8B-parameter transformer in bf16, showing the arithmetic.",
  },
];

const WORKFLOW_DEFAULTS = {
  structure: "vertical",
  agent_count: 3,
  max_iterations: 3,
};
