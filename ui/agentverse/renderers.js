/* DOM renderers (parity: reference ui/agentverse/renderers.js).
 * Pure state -> DOM functions; app.js calls renderAll after every event. */

const STAGE_ORDER = ["recruitment", "decision", "execution", "evaluation"];

function renderStages(state) {
  const iter = state.iterations.get(state.currentIteration);
  $("stages").innerHTML = STAGE_ORDER.map((name) => {
    const st = iter?.stages.get(name);
    const cls = st ? st.status : "pending";
    return `<div class="stage ${cls}" id="stage-${name}">
      <h4>${name}</h4>
      <div class="detail">${st ? renderStageDetail(name, st.detail) : "waiting…"}</div>
    </div>`;
  }).join("");
}

function renderStageDetail(name, d) {
  if (!d) return "";
  if (name === "recruitment" && d.experts) {
    return d.experts.map((e) =>
      `<div class="expert"><strong>${escapeHtml(e.name ?? "expert")}
         ${e.expertise ? " · " + escapeHtml(e.expertise) : ""}</strong>
       <span>${escapeHtml(truncate(e.responsibility ?? e.description ?? "", 140))}</span></div>`).join("");
  }
  if (name === "decision" && (d.plan || d.structure)) {
    return `${d.structure ? `<em>${escapeHtml(d.structure)}</em> ` : ""}
            ${escapeHtml(truncate(d.plan ?? "", 280))}`;
  }
  if (name === "evaluation" && (d.score != null || d.overall_score != null)) {
    const score = d.score ?? d.overall_score;
    const ok = d.goal_achieved ? "achieved" : "not achieved";
    return `<span class="score">${escapeHtml(String(score))}/100</span> — goal ${ok}
            <div>${escapeHtml(truncate(d.feedback ?? "", 200))}</div>`;
  }
  const brief = Object.entries(d)
    .filter(([k]) => !["event", "stage", "iteration"].includes(k))
    .map(([k, v]) => `${k}: ${escapeHtml(truncate(
      typeof v === "string" ? v : JSON.stringify(v), 110))}`);
  return brief.slice(0, 4).join("<br>");
}

function renderIterations(state) {
  const el = $("iterations");
  if (!el) return;
  const parts = [];
  for (const [n, iter] of [...state.iterations.entries()].sort((a, b) => a[0] - b[0])) {
    const score = state.scores.find((s) => s.iteration === n);
    const active = n === state.currentIteration ? "active" : "";
    parts.push(`<button class="iter-tab ${active}" data-iter="${n}">
      iter ${n}${score ? ` · ${score.score}` : ""}</button>`);
  }
  el.innerHTML = parts.join("");
}

function renderDiscussion(state) {
  const iter = state.iterations.get(state.currentIteration);
  const el = $("discussion");
  if (!el) return;
  const rows = [];
  for (const t of iter?.discussion ?? []) {
    const msg = t.message ?? "";
    rows.push(`<div class="turn">
      <span class="who">R${t.round ?? "?"} · ${escapeHtml(t.expert ?? "expert")}</span>
      <span>${escapeHtml(truncate(msg, 400))}</span>
      ${msg.includes("[CONSENSUS]") ? '<span class="tag">[CONSENSUS]</span>' : ""}</div>`);
  }
  for (const v of iter?.vertical ?? []) {
    const text = v.plan_preview ?? v.message ?? "";
    rows.push(`<div class="turn vertical">
      <span class="who">v${v.vertical_round ?? "?"} · ${escapeHtml(v.role ?? "")}
        ${v.expert ? " · " + escapeHtml(v.expert) : ""}</span>
      <span>${escapeHtml(truncate(text, 400))}</span>
      ${String(text).includes("[APPROVED]") ? '<span class="tag">[APPROVED]</span>' : ""}</div>`);
  }
  for (const x of iter?.executions ?? []) {
    rows.push(`<div class="turn exec">
      <span class="who">exec · ${escapeHtml(x.expert ?? "")}</span>
      <span>${escapeHtml(truncate(x.result_preview ?? x.result ?? "", 400))}</span>
      ${x.ok === false ? '<span class="tag err">ERR</span>' : ""}</div>`);
  }
  el.innerHTML = rows.length ? rows.join("") : '<div class="muted">no turns yet</div>';
}

function renderCalls(state) {
  const rows = state.calls.map((c) => `<tr class="${c.error ? "err" : ""}">
    <td>${escapeHtml(c.stage ?? "")}</td>
    <td>${c.iteration ?? ""}</td>
    <td>${escapeHtml(truncate(c.request_id ?? "", 10))}</td>
    <td>${fmtMs(c.latency_ms)}</td>
    <td>${fmtNum(c.prompt_tokens)}</td>
    <td>${fmtNum(c.completion_tokens)}</td>
    <td>${c.error ? "ERR" : escapeHtml(String(c.status ?? "ok"))}</td></tr>`);
  $("calls").querySelector("tbody").innerHTML = rows.join("");
}

function renderTotals(state) {
  const el = $("totals");
  if (!el) return;
  const t = state.totals;
  el.innerHTML = `
    <span><b>${fmtNum(t.calls)}</b> calls</span>
    <span><b>${fmtNum(t.errors)}</b> errors</span>
    <span><b>${fmtNum(t.prompt_tokens)}</b> prompt tok</span>
    <span><b>${fmtNum(t.completion_tokens)}</b> compl tok</span>
    <span><b>${fmtMs(t.latency_ms)}</b> cumulative latency</span>
    <span><b>${fmtUsd(t.cost_usd || null)}</b> est. cost</span>`;
}

function renderEvents(state) {
  $("events").innerHTML = state.events.slice(0, 120).map((e) =>
    `<div><span class="ts">${e.at}</span>
     <span class="evt">${escapeHtml(e.event)}</span>
     ${escapeHtml(truncate(JSON.stringify(e), 200))}</div>`).join("");
}

/* Swim-lane SVG of every request the run made (parity: reference
 * renderers.js renderLlmRequestsGraph): three actor lanes — Agent A,
 * Agent B workers, LLM backend — time flowing downward, one row per
 * orchestrator LLM call (A→LLM, labeled by stage) or worker execution
 * (A→B→LLM→back, labeled by expert). Tooltips carry latency/tokens. */
function renderFlowGraph(state) {
  const el = $("flow");
  if (!el) return;
  const rows = [];
  for (let i = state.events.length - 1; i >= 0; i--) {   // chronological
    const e = state.events[i];
    if (e.event === "llm_request" || e.event === "llm_error") {
      rows.push({ kind: "llm", at: e.at, label: e.stage ?? "call",
                  err: e.event === "llm_error" || !!e.error,
                  tip: `${e.stage ?? "call"} · iter ${e.iteration ?? "?"} · ` +
                       `${fmtMs(e.latency_ms)} · ${fmtNum(e.prompt_tokens)}p/` +
                       `${fmtNum(e.completion_tokens)}c tok` });
    } else if (e.event === "execution_result") {
      rows.push({ kind: "worker", at: e.at, label: e.expert ?? "worker",
                  err: e.ok === false,
                  tip: `exec · iter ${e.iteration ?? "?"} · ` +
                       `${e.expert ?? "worker"}` });
    }
  }
  if (!rows.length) {
    el.innerHTML = '<div class="muted">no requests yet</div>';
    return;
  }
  // Bounded like renderEvents' 120-entry cap: this repaints per event, and
  // an unbounded SVG rebuild would be O(run length) DOM work each time.
  const MAX_FLOW_ROWS = 100;
  const dropped = rows.length - MAX_FLOW_ROWS;
  if (dropped > 0) rows.splice(0, dropped);
  const laneX = { a: 70, b: 230, llm: 390 };
  const width = 460, rowH = 30, top = 34;
  const height = top + rows.length * rowH + 16;
  const parts = [`<svg viewBox="0 0 ${width} ${height}" class="flow-svg"
    preserveAspectRatio="xMidYMin meet">`];
  for (const [key, name] of [["a", "Agent A"], ["b", "Agent B"], ["llm", "LLM backend"]]) {
    parts.push(`<line class="lane" x1="${laneX[key]}" y1="${top - 8}"
      x2="${laneX[key]}" y2="${height - 10}"></line>
      <text class="lane-label" x="${laneX[key]}" y="16"
        text-anchor="middle">${name}</text>`);
  }
  rows.forEach((r, idx) => {
    const y = top + idx * rowH + rowH / 2;
    const cls = r.err ? "flow-err" : "flow-ok";
    const tip = `<title>${escapeHtml(`${r.at} — ${r.tip}`)}</title>`;
    if (r.kind === "llm") {
      parts.push(`<g class="${cls}">${tip}
        <line class="edge" x1="${laneX.a}" y1="${y}" x2="${laneX.llm}" y2="${y}"
          marker-end="url(#arrow)"></line>
        <circle cx="${laneX.llm}" cy="${y}" r="5"></circle>
        <text class="edge-label" x="${(laneX.a + laneX.llm) / 2}" y="${y - 5}"
          text-anchor="middle">${escapeHtml(truncate(r.label, 24))}</text></g>`);
    } else {
      parts.push(`<g class="${cls}">${tip}
        <line class="edge" x1="${laneX.a}" y1="${y}" x2="${laneX.b}" y2="${y}"
          marker-end="url(#arrow)"></line>
        <line class="edge dashed" x1="${laneX.b}" y1="${y}" x2="${laneX.llm}" y2="${y}"></line>
        <line class="edge dashed" x1="${laneX.b}" y1="${y + 8}" x2="${laneX.a}" y2="${y + 8}"></line>
        <circle cx="${laneX.b}" cy="${y}" r="5"></circle>
        <text class="edge-label" x="${(laneX.a + laneX.b) / 2}" y="${y - 5}"
          text-anchor="middle">${escapeHtml(truncate(r.label, 18))}</text></g>`);
    }
  });
  parts.push(`<defs><marker id="arrow" markerWidth="8" markerHeight="8"
    refX="7" refY="3" orient="auto"><path d="M0,0 L7,3 L0,6 z"></path>
    </marker></defs></svg>`);
  el.innerHTML = parts.join("");
}

/* Score progression across iterations (parity: reference renderers.js
 * renderIterationHistory): one bar per iteration colored by the success
 * threshold bands, with the score delta vs the previous iteration. */
function renderHistory(state) {
  const el = $("history");
  if (!el) return;
  if (!state.scores.length) {
    el.innerHTML = '<div class="muted">no evaluations yet</div>';
    return;
  }
  const sorted = [...state.scores].sort((a, b) => a.iteration - b.iteration);
  const bars = sorted.map((s, i) => {
    const band = s.score >= 70 ? "good" : s.score >= 40 ? "mid" : "bad";
    const prev = i > 0 ? sorted[i - 1].score : null;
    const delta = prev == null ? "" : (s.score >= prev ? "▲" : "▼") +
      Math.abs(Math.round(s.score - prev));
    return `<div class="hist-col" title="iteration ${s.iteration}: ${s.score}/100">
      <div class="hist-delta ${s.score >= (prev ?? s.score) ? "up" : "down"}">${delta}</div>
      <div class="hist-bar ${band}" style="height:${Math.max(4, s.score)}px"></div>
      <div class="hist-score">${Math.round(s.score)}</div>
      <div class="hist-iter">it ${s.iteration}</div>
    </div>`;
  });
  el.innerHTML = `<div class="hist-row">${bars.join("")}</div>`;
}

function renderFinal(state) {
  if (state.error) {
    $("final").textContent = `workflow error: ${state.error}`;
    $("final").classList.add("error");
  } else if (state.finalOutput) {
    $("final").textContent = state.finalOutput;
    $("final").classList.remove("error");
  }
}

function renderAll(state) {
  renderStages(state);
  renderIterations(state);
  renderDiscussion(state);
  renderCalls(state);
  renderTotals(state);
  renderFlowGraph(state);
  renderHistory(state);
  renderEvents(state);
  renderFinal(state);
}

/* Repaint only the panels an event can affect — renderAll on every SSE
 * event is O(run length) DOM work per event and janks long runs. */
const EVENT_PANELS = {
  iteration_start: [renderIterations, renderStages],
  iteration_complete: [renderIterations],
  stage_start: [renderStages],
  stage_complete: [renderStages, renderIterations, renderHistory],
  discussion_round: [renderDiscussion],
  vertical_iteration: [renderDiscussion],
  execution_result: [renderDiscussion, renderFlowGraph],
  llm_request: [renderCalls, renderTotals, renderFlowGraph],
  llm_error: [renderCalls, renderTotals, renderFlowGraph],
};

function renderFor(state, eventName) {
  const panels = EVENT_PANELS[eventName];
  if (!panels) { renderAll(state); return; }   // complete/error/unknown
  for (const fn of panels) fn(state);
  renderEvents(state);
}
