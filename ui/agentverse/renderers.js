/* DOM renderers (parity: reference ui/agentverse/renderers.js).
 * Pure state -> DOM functions; app.js calls renderAll after every event. */

const STAGE_ORDER = ["recruitment", "decision", "execution", "evaluation"];

function renderStages(state) {
  const iter = state.iterations.get(state.currentIteration);
  $("stages").innerHTML = STAGE_ORDER.map((name) => {
    const st = iter?.stages.get(name);
    const cls = st ? st.status : "pending";
    return `<div class="stage ${cls}" id="stage-${name}">
      <h4>${name}</h4>
      <div class="detail">${st ? renderStageDetail(name, st.detail) : "waiting…"}</div>
    </div>`;
  }).join("");
}

function renderStageDetail(name, d) {
  if (!d) return "";
  if (name === "recruitment" && d.experts) {
    return d.experts.map((e) =>
      `<div class="expert"><strong>${escapeHtml(e.name ?? "expert")}
         ${e.expertise ? " · " + escapeHtml(e.expertise) : ""}</strong>
       <span>${escapeHtml(truncate(e.responsibility ?? e.description ?? "", 140))}</span></div>`).join("");
  }
  if (name === "decision" && (d.plan || d.structure)) {
    return `${d.structure ? `<em>${escapeHtml(d.structure)}</em> ` : ""}
            ${escapeHtml(truncate(d.plan ?? "", 280))}`;
  }
  if (name === "evaluation" && (d.score != null || d.overall_score != null)) {
    const score = d.score ?? d.overall_score;
    const ok = d.goal_achieved ? "achieved" : "not achieved";
    return `<span class="score">${escapeHtml(String(score))}/100</span> — goal ${ok}
            <div>${escapeHtml(truncate(d.feedback ?? "", 200))}</div>`;
  }
  const brief = Object.entries(d)
    .filter(([k]) => !["event", "stage", "iteration"].includes(k))
    .map(([k, v]) => `${k}: ${escapeHtml(truncate(
      typeof v === "string" ? v : JSON.stringify(v), 110))}`);
  return brief.slice(0, 4).join("<br>");
}

function renderIterations(state) {
  const el = $("iterations");
  if (!el) return;
  const parts = [];
  for (const [n, iter] of [...state.iterations.entries()].sort((a, b) => a[0] - b[0])) {
    const score = state.scores.find((s) => s.iteration === n);
    const active = n === state.currentIteration ? "active" : "";
    parts.push(`<button class="iter-tab ${active}" data-iter="${n}">
      iter ${n}${score ? ` · ${score.score}` : ""}</button>`);
  }
  el.innerHTML = parts.join("");
}

function renderDiscussion(state) {
  const iter = state.iterations.get(state.currentIteration);
  const el = $("discussion");
  if (!el) return;
  const rows = [];
  for (const t of iter?.discussion ?? []) {
    const msg = t.message ?? "";
    rows.push(`<div class="turn">
      <span class="who">R${t.round ?? "?"} · ${escapeHtml(t.expert ?? "expert")}</span>
      <span>${escapeHtml(truncate(msg, 400))}</span>
      ${msg.includes("[CONSENSUS]") ? '<span class="tag">[CONSENSUS]</span>' : ""}</div>`);
  }
  for (const v of iter?.vertical ?? []) {
    const text = v.plan_preview ?? v.message ?? "";
    rows.push(`<div class="turn vertical">
      <span class="who">v${v.vertical_round ?? "?"} · ${escapeHtml(v.role ?? "")}
        ${v.expert ? " · " + escapeHtml(v.expert) : ""}</span>
      <span>${escapeHtml(truncate(text, 400))}</span>
      ${String(text).includes("[APPROVED]") ? '<span class="tag">[APPROVED]</span>' : ""}</div>`);
  }
  for (const x of iter?.executions ?? []) {
    rows.push(`<div class="turn exec">
      <span class="who">exec · ${escapeHtml(x.expert ?? "")}</span>
      <span>${escapeHtml(truncate(x.result_preview ?? x.result ?? "", 400))}</span>
      ${x.ok === false ? '<span class="tag err">ERR</span>' : ""}</div>`);
  }
  el.innerHTML = rows.length ? rows.join("") : '<div class="muted">no turns yet</div>';
}

function renderCalls(state) {
  const rows = state.calls.map((c) => `<tr class="${c.error ? "err" : ""}">
    <td>${escapeHtml(c.stage ?? "")}</td>
    <td>${c.iteration ?? ""}</td>
    <td>${escapeHtml(truncate(c.request_id ?? "", 10))}</td>
    <td>${fmtMs(c.latency_ms)}</td>
    <td>${fmtNum(c.prompt_tokens)}</td>
    <td>${fmtNum(c.completion_tokens)}</td>
    <td>${c.error ? "ERR" : escapeHtml(String(c.status ?? "ok"))}</td></tr>`);
  $("calls").querySelector("tbody").innerHTML = rows.join("");
}

function renderTotals(state) {
  const el = $("totals");
  if (!el) return;
  const t = state.totals;
  el.innerHTML = `
    <span><b>${fmtNum(t.calls)}</b> calls</span>
    <span><b>${fmtNum(t.errors)}</b> errors</span>
    <span><b>${fmtNum(t.prompt_tokens)}</b> prompt tok</span>
    <span><b>${fmtNum(t.completion_tokens)}</b> compl tok</span>
    <span><b>${fmtMs(t.latency_ms)}</b> cumulative latency</span>
    <span><b>${fmtUsd(t.cost_usd || null)}</b> est. cost</span>`;
}

function renderEvents(state) {
  $("events").innerHTML = state.events.slice(0, 120).map((e) =>
    `<div><span class="ts">${e.at}</span>
     <span class="evt">${escapeHtml(e.event)}</span>
     ${escapeHtml(truncate(JSON.stringify(e), 200))}</div>`).join("");
}

function renderFinal(state) {
  if (state.error) {
    $("final").textContent = `workflow error: ${state.error}`;
    $("final").classList.add("error");
  } else if (state.finalOutput) {
    $("final").textContent = state.finalOutput;
    $("final").classList.remove("error");
  }
}

function renderAll(state) {
  renderStages(state);
  renderIterations(state);
  renderDiscussion(state);
  renderCalls(state);
  renderTotals(state);
  renderEvents(state);
  renderFinal(state);
}

/* Repaint only the panels an event can affect — renderAll on every SSE
 * event is O(run length) DOM work per event and janks long runs. */
const EVENT_PANELS = {
  iteration_start: [renderIterations, renderStages],
  iteration_complete: [renderIterations],
  stage_start: [renderStages],
  stage_complete: [renderStages, renderIterations],
  discussion_round: [renderDiscussion],
  vertical_iteration: [renderDiscussion],
  execution_result: [renderDiscussion],
  llm_request: [renderCalls, renderTotals],
  llm_error: [renderCalls, renderTotals],
};

function renderFor(state, eventName) {
  const panels = EVENT_PANELS[eventName];
  if (!panels) { renderAll(state); return; }   // complete/error/unknown
  for (const fn of panels) fn(state);
  renderEvents(state);
}
