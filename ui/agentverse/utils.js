/* Shared helpers (parity: reference ui/agentverse/utils.js). */

const $ = (id) => document.getElementById(id);

function escapeHtml(s) {
  return String(s ?? "").replace(/[&<>"']/g, (c) => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;",
  }[c]));
}

function truncate(s, n) {
  s = String(s ?? "");
  return s.length > n ? s.slice(0, n - 1) + "…" : s;
}

function fmtMs(ms) {
  if (ms == null || ms === "") return "—";
  const n = Number(ms);
  return n >= 1000 ? (n / 1000).toFixed(1) + " s" : Math.round(n) + " ms";
}

function fmtNum(n) {
  return n == null ? "—" : Number(n).toLocaleString();
}

function fmtUsd(x) {
  return x == null ? "—" : "$" + Number(x).toFixed(4);
}

function clockNow() {
  return new Date().toLocaleTimeString();
}
