#!/usr/bin/env python3
"""Disaggregated prefill/decode serving A/B driver (round 16).

Two pools over the SAME shared runner, same seats, same trace:

  mixed   — 2 mixed replicas (the LLM_POOL_ROLES-unset shape; migration
            on, so the only config delta between the arms is the roles).
  disagg  — 1 prefill-role + 1 decode-role replica: every stream
            prefills on replica 0, hands its KV to replica 1 after the
            first sampled token (trigger="disagg"), and decodes there.

Per arm, two measurements:

  * the round-15 agentic open-loop λ sweep (synthesized AgentVerse DAG
    trace, poisson arrivals) → TTFT-attainment capacity knee
    (`*_max_sustainable_lambda`);
  * a prefill-interference probe: N decode streams mid-flight, then one
    LONG prompt (8k-class on TPU, scaled down on CPU) lands — decode
    ITL p99 over the client-observed token gaps is the headline. On a
    mixed pool the long prefill stalls its replica's decode batchmates
    (prefill-priority admission); on the disagg pool the decode tier
    never sees it.

Gates (machine-checked here and in tests/test_scripts.py):

  * every request terminates, nothing shed/errored in either arm;
  * EXACT counter reconciliation: the disagg arm's
    (disagg, adopted) migration count equals the number of streams that
    outlived their first decode dispatch — each hands off exactly once,
    finished-at-first-token streams never do — and (disagg, failed) is
    zero; the mixed arm records zero migrations.

bench.py's `disagg_ab` probe imports `run_disagg_ab` from this file
(the spec_ab pattern), so the bench arm and this driver can never
drift while measuring under the same names.

Usage: python scripts/dev/disagg_ab.py [tasks] [max_tokens] [decoders]
Env: DISAGG_AB_MODEL (default tiny/fp32 on cpu, llama-3.2-1b/bf16 on
     tpu), DISAGG_AB_RATES (comma λ list, default "8,16" cpu /
     "16,32" tpu), DISAGG_AB_TARGET (attainment target for the knee,
     default 0.99 tpu / 0.5 cpu — the tiny-engine knee).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

MIXED = ("mixed", "mixed")
DISAGG = ("prefill", "decode")


def _percentile(values, q):
    if not values:
        return None
    v = sorted(values)
    return v[min(len(v) - 1, int(q * len(v)))]


def build_pool(roles, *, model, dtype, model_cfg, runner, seats,
               max_len, num_blocks):
    """One pool arm; engines share the runner (weights compiled once)."""
    from agentic_traffic_testing_tpu.runtime.engine import (
        EngineConfig,
        LLMEngine,
    )
    from agentic_traffic_testing_tpu.serving.replica_pool import EnginePool

    engines = [LLMEngine(EngineConfig(
        model=model, dtype=dtype, max_num_seqs=seats,
        max_model_len=max_len, block_size=16, num_blocks=num_blocks,
        migration=1,
        disagg_role="" if role == "mixed" else role,
    ), model_cfg=model_cfg, runner=runner) for role in roles]
    return EnginePool(engines, policy="round_robin")


def first_window(cfg_or_pool, runner) -> int:
    """Max tokens a stream can emit before the prefill-role handoff hook
    is guaranteed to have seen it live: the pipelined engine harvests up
    to `pipeline_depth + 1` in-flight dispatches of `decode_steps`
    tokens on top of the prefill's first token, so a stream whose budget
    fits inside that window may finish before the hook runs."""
    cfg = getattr(cfg_or_pool, "engines", None)
    pd = (cfg_or_pool.engines[0].cfg.pipeline_depth if cfg
          else cfg_or_pool.pipeline_depth)
    return 1 + max(1, getattr(runner, "decode_steps", 1)) * (pd + 1)


def reconcile(pool, records, runner) -> dict:
    """The exact-counter gate. On a disagg pool every stream whose token
    budget exceeds the first harvest window hands off exactly once, and
    a stream finishing at its first sampled token never does; budgets
    INSIDE the window are schedule-dependent (the stream may finish
    before the handoff hook sees it), so the drivers here keep every
    budget out of that band — `ambiguous` streams make the gate fail
    loudly rather than silently fudge. A mixed pool must record zero."""
    adopted = pool.migrations.get(("disagg", "adopted"), 0)
    failed = pool.migrations.get(("disagg", "failed"), 0)
    win = first_window(pool, runner)
    ambiguous = sum(1 for r in records if 1 < r.n_tokens <= win)
    expected = (sum(1 for r in records if r.n_tokens > win)
                if pool.roles_active else 0)
    return {
        "migrations_adopted": adopted,
        "migrations_failed": failed,
        "expected_handoffs": expected,
        "counters_reconcile": (failed == 0 and ambiguous == 0
                               and adopted == expected),
    }


def run_sweep(roles, rates, trace, vocab, **pool_kw) -> tuple:
    """Replay the trace open-loop at each λ against a FRESH pool (clean
    per-rate counters); returns (sweep rows, keyed report, reconcile_ok).
    """
    from agentic_traffic_testing_tpu.loadgen.replay import (
        replay_against_engine,
    )

    sweep, keyed = [], {}
    reconcile_ok = True
    adopted_total = 0
    for lam in rates:
        pool = build_pool(roles, **pool_kw)
        try:
            records, report = replay_against_engine(
                pool, trace, arrival="poisson", rate=lam, seed=13,
                vocab_size=vocab)
        finally:
            pool.shutdown()
        if not report["all_terminated"]:
            raise RuntimeError(
                f"disagg_ab gate: requests left unterminated at rate "
                f"{lam}")
        if report["completed"] != report["requests"]:
            raise RuntimeError(
                f"disagg_ab gate: {report['requests'] - report['completed']}"
                f" request(s) shed/errored at rate {lam} — the A/B must "
                f"run clean")
        rec = reconcile(pool, records, pool_kw["runner"])
        reconcile_ok = reconcile_ok and rec["counters_reconcile"]
        adopted_total += rec["migrations_adopted"]
        sweep.append((lam, report))
        itls = [r.mean_itl_s for r in records
                if r.status == "ok" and r.mean_itl_s is not None]
        keyed[f"r{lam:g}_ttft_attainment"] = report["ttft_attainment"]
        keyed[f"r{lam:g}_goodput_rate"] = report["goodput_rate"]
        keyed[f"r{lam:g}_itl_p99_s"] = _percentile(itls, 0.99)
    return sweep, keyed, reconcile_ok, adopted_total


def interference_probe(roles, *, decoders, decode_tokens, prefill_len,
                       vocab, **pool_kw) -> dict:
    """Decode ITL under a concurrent LONG prefill: start `decoders`
    streams, wait for every one to reach decode (handed off, on a
    disagg pool), then land one `prefill_len`-token prompt and keep
    streaming. Reports the client-observed inter-token-gap p99 of the
    decode streams and the exact handoff reconciliation."""
    import asyncio

    import numpy as np

    from agentic_traffic_testing_tpu.runtime.request import SamplingParams

    rng = np.random.default_rng(19)
    pool_kw = dict(pool_kw)
    pool_kw["max_len"] = max(pool_kw["max_len"], prefill_len + 64)
    bs = 16
    pool_kw["num_blocks"] = max(
        pool_kw["num_blocks"],
        2 * (-(-pool_kw["max_len"] // bs) + 4) * (decoders + 2))
    pool = build_pool(roles, **pool_kw)
    gaps: list = []
    n_tokens = {}

    async def decode_stream(i):
        prompt = rng.integers(10, vocab, 24).tolist()
        last = None
        toks = 0
        async for ev in pool.generate(
                prompt, SamplingParams(temperature=0.0,
                                       max_tokens=decode_tokens,
                                       ignore_eos=True),
                request_id=f"dec{i}"):
            now = time.monotonic()
            if ev.new_token_ids:
                if last is not None:
                    gaps.append(now - last)
                last = now
                toks += len(ev.new_token_ids)
        n_tokens[f"dec{i}"] = toks

    # Budget the long request past the first harvest window too, so it
    # is itself a guaranteed (and exactly counted) handoff.
    long_tokens = first_window(pool, pool_kw["runner"]) + 2

    async def long_prefill():
        prompt = rng.integers(10, vocab, prefill_len).tolist()
        toks = 0
        async for ev in pool.generate(
                prompt, SamplingParams(temperature=0.0,
                                       max_tokens=long_tokens,
                                       ignore_eos=True),
                request_id="long"):
            toks += len(ev.new_token_ids)
        n_tokens["long"] = toks

    async def go():
        streams = [asyncio.ensure_future(decode_stream(i))
                   for i in range(decoders)]
        # Let every stream clear prefill (and, disaggregated, hand off)
        # before the interference lands.
        while not all(f"dec{i}" in n_tokens or gaps for i in
                      range(decoders)):
            await asyncio.sleep(0.05)
            if all(f.done() for f in streams):
                break
        lp = asyncio.ensure_future(long_prefill())
        await asyncio.gather(*streams, lp)

    pool.start()
    try:
        asyncio.run(go())
    finally:
        pool.shutdown()

    class _Rec:  # reconcile() reads .n_tokens only
        def __init__(self, n):
            self.n_tokens = n

    rec = reconcile(pool, [_Rec(n) for n in n_tokens.values()],
                    pool_kw["runner"])
    return {
        "interference_prefill_tokens": prefill_len,
        "interference_decode_streams": decoders,
        "interference_itl_p99_s": _percentile(gaps, 0.99),
        "interference_itl_p50_s": _percentile(gaps, 0.50),
        **{f"interference_{k}": v for k, v in rec.items()},
    }


def run_disagg_ab(*, model, dtype, model_cfg, runner, tasks=2, seed=9,
                  max_tokens=10, rates=(8.0, 16.0), seats=4,
                  long_prefill=96, decoders=3, decode_tokens=24,
                  target=0.5) -> dict:
    """The full A/B under one roof — bench.py's `disagg_ab` probe calls
    exactly this. Returns the flat keyed dict bench merges into its
    report."""
    from agentic_traffic_testing_tpu.loadgen.measure import capacity_knee
    from agentic_traffic_testing_tpu.loadgen.replay import engine_geometry
    from agentic_traffic_testing_tpu.loadgen.trace import (
        synthesize_agentverse_trace,
    )

    from agentic_traffic_testing_tpu.runtime.engine import EngineConfig

    # Keep every stream's budget ABOVE the pipelined first-harvest
    # window (see first_window): the smallest trace node budget is
    # max(4, max_tokens // 4), so raise the trace knob until even that
    # clears the window and the handoff count becomes exactly
    # predictable from the records.
    win = first_window(
        EngineConfig(model=model, dtype=dtype, max_num_seqs=seats,
                     max_model_len=256, block_size=16, num_blocks=64,
                     migration=1), runner)
    max_tokens = max(max_tokens, 4 * (win + 1))
    decode_tokens = max(decode_tokens, win + 8)

    trace = synthesize_agentverse_trace(tasks=tasks, seed=seed,
                                        max_tokens=max_tokens)
    max_len, num_blocks = engine_geometry(trace, seats)
    pool_kw = dict(model=model, dtype=dtype, model_cfg=model_cfg,
                   runner=runner, seats=seats, max_len=max_len,
                   num_blocks=num_blocks)
    rates = [float(r) for r in rates]

    # Discarded warmup pass (compiles every trace shape off the clock).
    run_sweep(MIXED, rates[:1], trace, model_cfg.vocab_size, **pool_kw)

    out: dict = {"disagg_ab_rates": rates,
                 "disagg_ab_trace_nodes": len(trace.nodes)}
    knees = {}
    for tag, roles in (("mixed", MIXED), ("disagg", DISAGG)):
        sweep, keyed, ok, adopted = run_sweep(
            roles, rates, trace, model_cfg.vocab_size, **pool_kw)
        knees[tag] = capacity_knee(sweep, target=target)
        out[f"agentic_load_{tag}_max_sustainable_lambda"] = knees[tag]
        out[f"{tag}_counters_reconcile"] = ok
        out[f"{tag}_migrations_adopted"] = adopted
        out.update({f"{tag}_{k}": v for k, v in keyed.items()})
        inter = interference_probe(
            roles, decoders=decoders, decode_tokens=decode_tokens,
            prefill_len=long_prefill, vocab=model_cfg.vocab_size,
            **pool_kw)
        out.update({f"{tag}_{k}": v for k, v in inter.items()})
    return out


def main(argv=None) -> dict:
    argv = [int(a) for a in (argv if argv is not None else sys.argv[1:])]
    tasks = argv[0] if len(argv) > 0 else 2
    max_tokens = argv[1] if len(argv) > 1 else 8
    decoders = argv[2] if len(argv) > 2 else 3

    import jax
    import jax.numpy as jnp

    from agentic_traffic_testing_tpu.models.config import resolve_config
    from agentic_traffic_testing_tpu.models.llama import init_params
    from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    model = os.environ.get(
        "DISAGG_AB_MODEL", "llama-3.2-1b" if on_tpu else "tiny")
    dtype = "bfloat16" if on_tpu else "float32"
    rates = [float(r) for r in os.environ.get(
        "DISAGG_AB_RATES", "16,32" if on_tpu else "8,16").split(",") if r]
    target = float(os.environ.get(
        "DISAGG_AB_TARGET", "0.99" if on_tpu else "0.5"))

    model_cfg = resolve_config(model)
    params = init_params(
        model_cfg, jax.random.key(0),
        dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    runner = ModelRunner(model_cfg, params,
                         decode_steps=16 if on_tpu else 1)
    print(f"devices: {jax.devices()}  rates={rates}", file=sys.stderr,
          flush=True)
    out = run_disagg_ab(
        model=model, dtype=dtype, model_cfg=model_cfg, runner=runner,
        tasks=tasks, max_tokens=max_tokens, rates=rates,
        seats=16 if on_tpu else 4,
        long_prefill=8192 if on_tpu else 96, decoders=decoders,
        target=target)
    print(json.dumps(out, indent=2), flush=True)
    ok = out["disagg_counters_reconcile"] and out["mixed_counters_reconcile"]
    return out if ok else (_ for _ in ()).throw(
        SystemExit("disagg_ab: counter reconciliation failed"))


if __name__ == "__main__":
    main()
