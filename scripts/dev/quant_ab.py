#!/usr/bin/env python3
"""A/B the round-5 quant hot spots on the real chip.

Two open questions from the round-5 hardware sweep (docs/BENCHMARKS.md
"Round-5" section):

  1. 8B int4 ~= int8 at bs=32 and LOSES at bs=16 — where does the int4
     kernel's per-step time go at the 8B's wide shapes?  A/B the
     first-party int4 kernel vs the XLA int8 convert+dot vs plain bf16
     at each 8B decode matmul shape, device-plane timed.
  2. fp8-KV costs 29% of bs=32 decode throughput — is the e4m3->f32
     VMEM cast inside the paged kernel really the whole story?  A/B the
     dma2 paged-decode kernel with bf16 vs float8_e4m3fn pages at the
     1B serving layout.

DEVICE time per call via the shared xplane harness (wall clock through
the axon tunnel is unusable for kernels — see xplane_util docstring).
For the XLA int8/bf16 matmuls there is no stable HLO name to match, so
this script sums ALL device-plane op time in a dedicated trace per
variant (the traced region runs nothing else).

Usage: python scripts/dev/quant_ab.py [matmul|paged]
"""

from __future__ import annotations

import glob
import os
import shutil
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
import jax.numpy as jnp

N = 8  # varied input sets per variant


def device_total_ms(fn, args_list, trace_dir: str) -> float:
    """Total device-plane op ms/call (all ops — the trace runs only fn)."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    jax.block_until_ready(fn(*args_list[0]))
    shutil.rmtree(trace_dir, ignore_errors=True)
    with jax.profiler.trace(trace_dir):
        outs = [fn(*a) for a in args_list]
        jax.block_until_ready(outs)
    paths = sorted(glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                             recursive=True))
    if not paths:
        raise RuntimeError(f"no .xplane.pb under {trace_dir}")
    xs = xplane_pb2.XSpace()
    with open(paths[-1], "rb") as f:
        xs.ParseFromString(f.read())
    tot_ps = 0
    for plane in xs.planes:
        if "TPU" not in plane.name:
            continue
        for line in plane.lines:
            lname = line.name.lower()
            if "module" in lname or "async" in lname:
                continue
            for ev in line.events:
                tot_ps += ev.duration_ps
    ms = tot_ps / 1e9 / len(args_list)
    if ms == 0.0:
        raise RuntimeError(f"no device events in trace under {trace_dir}")
    return ms


def matmul_ab() -> None:
    """int4 kernel vs int8 XLA vs bf16 at the llama-3.1-8b decode shapes."""
    from agentic_traffic_testing_tpu.models.quant import (
        quantize_array, quantize_array4,
    )
    from agentic_traffic_testing_tpu.models import quant

    # (K, N): qkv fused, o-proj, gate+up fused, down-proj.
    shapes = [(4096, 6144), (4096, 4096), (4096, 28672), (14336, 4096)]
    for b in (32, 16):
        print(f"--- 8B decode matmuls, rows={b} bf16 activations", flush=True)
        for k, n in shapes:
            key = jax.random.key(k + n)
            w = jax.random.normal(key, (k, n), jnp.float32) * 0.02
            q8 = quantize_array(w)          # QTensor (int8 + scale)
            q4 = quantize_array4(w)         # QTensor4 (packed nibbles)
            xs = [jax.random.normal(jax.random.key(7 * i), (b, k),
                                    jnp.bfloat16) for i in range(N)]
            stream_i4 = k * n / 2
            stream_i8 = k * n
            stream_bf = k * n * 2

            def f_bf16(x, _w=jnp.asarray(w, jnp.bfloat16)):
                return x @ _w

            def f_int8(x, _q=q8):
                return quant.dense(x, _q)

            def f_int4(x, _q=q4):
                return quant.dense(x, _q)

            row = [f"  [{k:>5d},{n:>5d}]"]
            for name, fn, byts in (("bf16", f_bf16, stream_bf),
                                   ("int8", f_int8, stream_i8),
                                   ("int4", f_int4, stream_i4)):
                ms = device_total_ms(jax.jit(fn), [(x,) for x in xs],
                                     f"/tmp/quant_ab_{name}_{k}_{n}_{b}")
                gbs = byts / (ms / 1e3) / 1e9
                row.append(f"{name} {ms:7.3f} ms ({gbs:5.0f} GB/s eff)")
            print("  ".join(row), flush=True)


def paged_ab() -> None:
    """dma2 paged decode: bf16 vs fp8 pages at the 1B serving layout."""
    from agentic_traffic_testing_tpu.ops.pallas.paged_attention import (
        paged_attention_decode_dma2,
    )

    b, h, kh, hd, bs = 32, 32, 8, 64, 16
    ctx = 176                      # ~128-token prompt + mid-completion
    blocks_per = (ctx + bs - 1) // bs
    nb = b * blocks_per + 1        # + trash block 0
    max_blocks = blocks_per
    bt = jnp.arange(1, nb, dtype=jnp.int32).reshape(b, max_blocks)
    cl = jnp.full((b,), ctx, jnp.int32)

    for dtype, tag in ((jnp.bfloat16, "bf16"), (jnp.float8_e4m3fn, "fp8")):
        args_list = []
        for i in range(N):
            kk = jax.random.key(17 * i)
            q = jax.random.normal(kk, (b, h, hd), jnp.bfloat16)
            kp = (jax.random.normal(jax.random.key(17 * i + 1),
                                    (kh, nb, bs, hd), jnp.bfloat16)
                  .astype(dtype))
            vp = (jax.random.normal(jax.random.key(17 * i + 2),
                                    (kh, nb, bs, hd), jnp.bfloat16)
                  .astype(dtype))
            args_list.append((q, kp, vp, bt, cl))
        fn = jax.jit(paged_attention_decode_dma2)
        ms = device_total_ms(fn, args_list, f"/tmp/quant_ab_paged_{tag}")
        kvb = 2 * kh * b * blocks_per * bs * hd * dtype(0).itemsize
        print(f"  paged dma2 {tag:<5s} pages: {ms:7.3f} ms/call DEVICE "
              f"({kvb / 1e6:.1f} MB KV streamed)", flush=True)


def main() -> None:
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    print(f"devices: {jax.devices()}", flush=True)
    if which in ("matmul", "all"):
        matmul_ab()
    if which in ("paged", "all"):
        paged_ab()


if __name__ == "__main__":
    main()
