#!/usr/bin/env python3
"""Measure prefill throughput + estimated MXU utilization on the real chip.

Round-3 verdict item #3: decode had a full streaming-bound anatomy
(profile_decode.py) but the compute-bound half of serving — prefill — had
no scoreboard. This times the engine's three prefill paths:

  solo     one prompt, single batched prefill dispatch (<= chunk threshold)
  chunked  one long prompt through the 2048-token chunk ladder
  batched  `fanout` prompts admitted together (prefill_batch_max_len)

and reports tok/s plus estimated MFU:

  MFU = model_flops_per_token * tokens / (wall * peak_flops)
  model_flops_per_token ~= 2 * active_params   (matmul FLOPs; attention
  adds O(T^2 * D) which is counted separately at longer lengths)

v5e peak: 197 bf16 TFLOP/s/chip. Timing is enqueue -> first token on host
minus one decode step (measured separately), i.e. the serving-visible
prefill cost, tunnel included — the honest number TTFT is made of.

Usage: python scripts/dev/profile_prefill.py [model] [lengths...]
"""

from __future__ import annotations

import os
import statistics
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

# Honor an explicit JAX_PLATFORMS=cpu despite the axon sitecustomize
# (wedged-tunnel hang trap - see agentic_traffic_testing_tpu/platform_guard.py).
from agentic_traffic_testing_tpu.platform_guard import force_cpu_if_requested  # noqa: E402

force_cpu_if_requested()


PEAK_FLOPS = {
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v6 lite": 918e12,
}


def param_count(params) -> int:
    import jax

    n = 0
    for leaf in jax.tree_util.tree_leaves(params):
        if hasattr(leaf, "size"):
            # int4 packed leaves hold two params per byte.
            n += leaf.size * (2 if leaf.dtype.name == "int8" and
                              "packed" in str(type(leaf)) else 1)
    return n


def main() -> None:
    import jax
    import numpy as np

    from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams

    model = sys.argv[1] if len(sys.argv) > 1 else os.environ.get(
        "BENCH_MODEL", "llama-3.2-1b")
    lengths = ([int(a) for a in sys.argv[2:]]
               or [512, 1024, 2048, 4096, 6144])
    reps = int(os.environ.get("BENCH_REPS", "3"))
    kind = jax.devices()[0].device_kind
    peak = PEAK_FLOPS.get(kind, 197e12)

    cfg = EngineConfig(
        model=model, dtype="bfloat16",
        max_num_seqs=4,
        max_model_len=max(lengths) + 64,
        decode_steps=None,
    )
    engine = LLMEngine(cfg)
    vocab = engine.model_cfg.vocab_size
    rng = np.random.default_rng(0)
    # 2 * active params: the dense matmul FLOPs per token (q/k/v/o + MLP +
    # unembed). Embedding gather is not a matmul; unembed IS counted (the
    # engine computes last-token logits only in prefill, so subtract it from
    # the per-token cost and add one instance per request).
    n_params = sum(l.size for l in jax.tree_util.tree_leaves(engine.runner.params))
    mc = engine.model_cfg
    unembed = mc.hidden_size * mc.vocab_size
    embed = mc.vocab_size * mc.hidden_size
    flops_tok = 2 * (n_params - unembed - embed)

    def run(prompt_len: int) -> float:
        ids = rng.integers(10, vocab - 10, prompt_len).tolist()
        req = engine.add_request(ids, SamplingParams(
            temperature=0.0, max_tokens=2, ignore_eos=True))
        while not req.is_finished():
            engine.step()
        return req.first_token_time - req.arrival_time

    for L in lengths:
        run(min(L, 256))  # warm compile for this bucket family
        ts = [run(L) for _ in range(reps)]
        t = statistics.median(ts)
        # attention FLOPs: 4 * D * T^2 per layer (QK^T + PV), causal halves
        attn = 2 * mc.num_layers * mc.hidden_size * L * L
        fl = flops_tok * L + attn + 2 * unembed
        print(f"len={L:5d}  prefill={t*1e3:8.1f} ms  "
              f"tok/s={L/t:9.0f}  est_mfu={fl/t/peak*100:5.1f}%  "
              f"spread=[{min(ts)*1e3:.0f},{max(ts)*1e3:.0f}]ms")


if __name__ == "__main__":
    main()
