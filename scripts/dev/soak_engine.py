#!/usr/bin/env python3
"""Mixed-workload soak for the serving engine on real hardware.

Drives combinations the unit suite exercises only in isolation, together:
staggered arrivals, prefix-cache-hit families, stop tokens, greedy and
sampled lanes, short token budgets, and mid-flight aborts — against the
throughput configuration (decode_steps=32, batched long prefills, prefix
caching). Asserts every request reaches a terminal state with a respected
token budget and that the KV pool fully drains (no block leak).

First run pays ~35 cold XLA bucket compiles through the tunnel, so the
printed tok/s is NOT a perf number — bench.py measures steady state.

Usage: python scripts/dev/soak_engine.py [num_requests]
Env: SOAK_MODEL (default llama-3.2-1b on TPU, tiny elsewhere).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

# Honor an explicit JAX_PLATFORMS=cpu despite the axon sitecustomize
# (wedged-tunnel hang trap - see agentic_traffic_testing_tpu/platform_guard.py).
from agentic_traffic_testing_tpu.platform_guard import force_cpu_if_requested  # noqa: E402

force_cpu_if_requested()


def main() -> None:
    import numpy as np

    from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams

    import jax

    platform = jax.devices()[0].platform
    model = os.environ.get(
        "SOAK_MODEL", "llama-3.2-1b" if platform == "tpu" else "tiny")
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 120

    cfg = EngineConfig(model=model, max_num_seqs=8, max_model_len=1024,
                       decode_steps=32 if platform == "tpu" else None,
                       num_blocks=None if platform == "tpu" else 512,
                       prefix_caching=True, prefill_batch_max_len=512)
    eng = LLMEngine(cfg)
    rng = np.random.default_rng(42)
    v = eng.model_cfg.vocab_size
    shared_prefix = rng.integers(10, v - 10, 160).tolist()

    pending = []
    for i in range(n):
        kind = i % 4
        if kind == 0:  # cache-hit family: shared prefix + short suffix
            ids = shared_prefix + rng.integers(
                10, v - 10, rng.integers(4, 40)).tolist()
        else:
            ids = rng.integers(10, v - 10, int(rng.integers(5, 600))).tolist()
        sp = SamplingParams(
            max_tokens=int(rng.integers(1, 100)),
            temperature=float(rng.choice([0.0, 0.0, 0.8])),
            top_k=int(rng.choice([0, 40])),
            ignore_eos=False,
            stop_token_ids=(int(rng.integers(10, v - 10)),) if kind == 2 else (),
            seed=i,
        )
        pending.append((ids, sp))

    t0 = time.monotonic()
    live, done, aborted, step_i = [], [], 0, 0
    while pending or eng.has_work():
        for _ in range(int(rng.integers(0, 4))):  # staggered arrivals
            if pending:
                ids, sp = pending.pop()
                live.append(eng.add_request(ids, sp))
        step_i += 1
        eng.step()
        if step_i % 37 == 0:  # occasional client disconnect
            cands = [r for r in live if not r.is_finished()]
            if cands:
                eng.abort_request(cands[int(rng.integers(0, len(cands)))])
                aborted += 1
        done.extend(r for r in live if r.is_finished())
        live = [r for r in live if not r.is_finished()]
        if step_i > 300 * n:
            raise SystemExit("soak wedged: step budget exhausted")
    dt = time.monotonic() - t0

    bad = []
    for r in done:
        k = len(r.generated_ids)
        if r.finish_reason is None:
            bad.append((r.request_id, "no finish reason"))
        elif r.finish_reason.name == "LENGTH" and k != r.sampling.max_tokens:
            bad.append((r.request_id, f"LENGTH with {k} != {r.sampling.max_tokens}"))
        if k > r.sampling.max_tokens:
            bad.append((r.request_id, f"overrun {k} > {r.sampling.max_tokens}"))
    assert not bad, bad[:5]
    toks = sum(len(r.generated_ids) for r in done)
    free, total = eng.allocator.num_free_blocks, eng.allocator.num_blocks - 1
    print(f"soak OK: {len(done)} finished ({aborted} aborted mid-flight), "
          f"{toks} tokens in {dt:.1f}s, {step_i} steps")
    print(f"KV accounting: free(incl. evictable)={free} total={total}")
    assert free == total, "KV block leak after full drain"
    print("no KV leak")


if __name__ == "__main__":
    main()
