#!/usr/bin/env python3
"""Chaos soak driver for the round-9 fault-tolerant serving plane.

Engine-level A/B isolated from the HTTP layer: the SAME churn workload
(more requests than seats, mixed greedy/seeded sampling, mixed stop
lengths) runs twice — `clean` (no faults, no deadlines, unbounded queue)
and `chaos` (a seeded LLM_FAULT_SPEC-style spec plus a bounded queue and
per-request deadlines on a slice of the workload). One JSON line per arm:

    {"mode": "clean"|"chaos", "completed": N, "errored": N, "shed": N,
     "deadline_expired": N, "dispatch_failures": N, "all_terminated": true,
     "unaffected_identical": true, ...}

Gates (the acceptance criteria of ISSUE 8, machine-checked here and in
tests/test_scripts.py::test_chaos_ab_smoke):

  * all_terminated      — every request reached a terminal state (completed,
                          shed, deadline, or structured error); none hung.
  * unaffected_identical — every request that COMPLETED under chaos produced
                          the clean arm's exact token stream (fault isolation:
                          a failing batch must not perturb survivors).
  * faults_accounted    — every fired injection shows up in a counter
                          (dispatch_failures + restore section's fallbacks).

A second section exercises the host-tier restore fallback: a scenario
prefix is computed, evicted to the host tier by capacity pressure
(offload_ab's recipe), then re-requested under restore_error:p=1 — the
restore degrades to recompute, the completion stays byte-identical, and
llm_host_restore_fallback_total accounts for it.

Usage: python scripts/dev/chaos_ab.py [n_requests] [prompt_len] [max_tokens]
Env: CHAOS_AB_MODEL (default: tiny fp32 on cpu, llama-3.2-1b bf16 on tpu),
     CHAOS_AB_SEATS (default 4 on cpu, 16 on tpu),
     CHAOS_AB_FAULT_SPEC (default "dispatch_error:p=0.05").
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def run_arm(chaos: bool, *, runner, model_cfg, model: str, dtype: str,
            seats: int, n_requests: int, prompt_len: int, max_tokens: int,
            fault_spec: str) -> dict:
    import numpy as np

    from agentic_traffic_testing_tpu.runtime.engine import (
        EngineConfig,
        LLMEngine,
    )
    from agentic_traffic_testing_tpu.runtime.request import (
        FinishReason,
        SamplingParams,
    )
    from agentic_traffic_testing_tpu.runtime.scheduler import QueueFullError

    block_size = 16
    max_len = max(256, prompt_len + max_tokens + 64)
    eng = LLMEngine(EngineConfig(
        model=model, dtype=dtype, max_num_seqs=seats, max_model_len=max_len,
        block_size=block_size,
        num_blocks=max(256, seats * (-(-max_len // block_size) + 4)),
        fault_spec=fault_spec if chaos else "",
        fault_seed=29,
        # Bound the queue only in the chaos arm: the clean arm is the
        # identity baseline and must admit everything.
        max_queue=n_requests if chaos else 0,
    ), model_cfg=model_cfg, runner=runner)

    wl = np.random.default_rng(31)  # reseeded per arm: identical workload
    vocab = model_cfg.vocab_size
    prompts = [wl.integers(10, vocab - 10, prompt_len).tolist()
               for _ in range(n_requests)]

    def sampling(i: int) -> SamplingParams:
        # Mixed greedy/seeded + mixed stop lengths = composition churn;
        # every 5th request in the chaos arm carries a generous deadline
        # (loose enough that only a fault-stalled queue can miss it —
        # the sweep machinery runs either way).
        deadline = 30_000.0 if (chaos and i % 5 == 4) else None
        if i % 2 == 0:
            return SamplingParams(temperature=0.0,
                                  max_tokens=max_tokens - (i % 3),
                                  ignore_eos=True, deadline_ms=deadline)
        return SamplingParams(temperature=0.8, top_k=20, seed=5 + i,
                              max_tokens=max_tokens // 2 + (i % 4),
                              ignore_eos=True, deadline_ms=deadline)

    reqs, shed = [], 0
    for i, p in enumerate(prompts):
        try:
            reqs.append(eng.add_request(p, sampling(i)))
        except QueueFullError:
            shed += 1
    t0 = time.monotonic()
    steps = 0
    step_cap = 200 * n_requests  # hang backstop: the gate below reports it
    while eng.has_work() and steps < step_cap:
        eng.step()
        steps += 1
    dt = time.monotonic() - t0

    completed = [r for r in reqs if r.finish_reason in
                 (FinishReason.STOP, FinishReason.LENGTH)]
    errored = [r for r in reqs if r.finish_reason is FinishReason.ERROR]
    deadline = [r for r in reqs if r.finish_reason is FinishReason.DEADLINE]
    return {
        "mode": "chaos" if chaos else "clean",
        "requests": n_requests,
        "seats": seats,
        "wall_s": round(dt, 3),
        "completed": len(completed),
        "errored": len(errored),
        "deadline_expired": len(deadline),
        "shed": shed + eng.num_shed,
        "dispatch_failures": eng.num_dispatch_failures,
        "all_terminated": all(r.is_finished() for r in reqs),
        "outputs": {i: r.output_ids for i, r in enumerate(reqs)
                    if r.finish_reason in (FinishReason.STOP,
                                           FinishReason.LENGTH)},
    }


def run_restore_section(*, runner, model_cfg, model: str,
                        dtype: str) -> dict:
    """Host-tier restore fallback under restore_error:p=1 (offload_ab's
    evict-then-rearrive recipe): the re-arrival degrades to recompute,
    stays byte-identical, and the fallback counter accounts for it."""
    import numpy as np

    from agentic_traffic_testing_tpu.runtime.engine import (
        EngineConfig,
        LLMEngine,
    )
    from agentic_traffic_testing_tpu.runtime.kv_offload import HostKVStore
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams

    block_size, prefix_len = 16, 96
    num_blocks = (-(-(prefix_len + 32) // block_size) + 3) + 1
    outs = {}
    counters = {}
    for mode in ("restore", "fallback"):
        eng = LLMEngine(EngineConfig(
            model=model, dtype=dtype, max_num_seqs=2,
            max_model_len=prefix_len + 96, block_size=block_size,
            num_blocks=num_blocks, prefix_caching=True,
            fault_spec="restore_error:p=1" if mode == "fallback" else "",
        ), model_cfg=model_cfg, runner=runner,
            host_store=HostKVStore(int(64e6)))
        wl = np.random.default_rng(11)
        vocab = model_cfg.vocab_size
        scenario = wl.integers(10, vocab - 10, prefix_len).tolist()
        pressures = [wl.integers(10, vocab - 10, prefix_len).tolist()
                     for _ in range(3)]
        sp = lambda: SamplingParams(temperature=0.0, max_tokens=8,
                                    ignore_eos=True)
        eng.generate(scenario, sp())
        for p in pressures:  # evict the scenario's blocks to the host tier
            eng.generate(p, sp())
        re_req = eng.generate(scenario, sp())
        outs[mode] = re_req.generated_ids
        counters[mode] = eng.num_restore_fallbacks
    return {
        "mode": "restore_fallback",
        "fallbacks": counters["fallback"],
        "clean_restores_fell_back": counters["restore"],
        "outputs_match": outs["restore"] == outs["fallback"],
    }


def _pool_workload(model_cfg, n_requests: int, prompt_len: int,
                   max_tokens: int):
    """Deterministic churn workload shared by the migration/scale arms:
    mixed greedy + seeded sampling, mixed stop lengths."""
    import numpy as np

    from agentic_traffic_testing_tpu.runtime.request import SamplingParams

    wl = np.random.default_rng(41)
    vocab = model_cfg.vocab_size
    prompts = [wl.integers(10, vocab - 10, prompt_len).tolist()
               for _ in range(n_requests)]

    def sampling(i: int) -> SamplingParams:
        if i % 2 == 0:
            return SamplingParams(temperature=0.0,
                                  max_tokens=max_tokens - (i % 3),
                                  ignore_eos=True)
        return SamplingParams(temperature=0.8, top_k=20, seed=5 + i,
                              max_tokens=max_tokens // 2 + (i % 4),
                              ignore_eos=True)

    return prompts, sampling


def _drive_pool(pool, prompts, sampling, step_cap: int,
                scale_script=None) -> dict:
    """Sync-drive a pool to completion, tracking each request's FINAL
    terminal (a migrated stream's later events carry a NEW Request object
    under the same request_id). `scale_script` maps a step index to a
    pool size (the scale-churn arm's oscillation)."""
    from agentic_traffic_testing_tpu.runtime.request import FinishReason

    reqs = [pool.add_request(p, sampling(i), request_id=f"m{i}")
            for i, p in enumerate(prompts)]
    finals = {r.request_id: r for r in reqs}
    steps = 0
    while pool.has_work() and steps < step_cap:
        if scale_script and steps in scale_script:
            for ev in pool.scale_to(scale_script[steps]):
                cur = finals.get(ev.request.request_id)
                if cur is None or ev.request.sampling_step >= cur.sampling_step:
                    finals[ev.request.request_id] = ev.request
        for ev in pool.step():
            cur = finals.get(ev.request.request_id)
            if cur is None or ev.request.sampling_step >= cur.sampling_step:
                finals[ev.request.request_id] = ev.request
        steps += 1
    done = {rid: r for rid, r in finals.items()
            if r.finish_reason in (FinishReason.STOP, FinishReason.LENGTH)}
    return {
        "steps": steps,
        "all_terminated": all(r.is_finished() for r in finals.values()),
        "completed": len(done),
        "errored": sum(1 for r in finals.values()
                       if r.finish_reason is FinishReason.ERROR),
        "outputs": {rid: r.generated_ids for rid, r in done.items()},
    }


def run_migration_soak(*, runner, model_cfg, model: str, dtype: str,
                       n_requests: int, prompt_len: int,
                       max_tokens: int) -> dict:
    """Round-11 migration soak: the same churn workload runs clean on a
    2-replica pool, then with dispatch faults injected on replica 0 and
    LLM_MIGRATION on — started streams checkpoint mid-decode and resume
    on the survivor. Gates: every stream terminates, at least one stream
    migrated, and every COMPLETED stream's tokens are byte-identical to
    the clean run's (the ISSUE-11 acceptance criterion)."""
    from agentic_traffic_testing_tpu.runtime.engine import (
        EngineConfig,
        LLMEngine,
    )
    from agentic_traffic_testing_tpu.serving.replica_pool import EnginePool

    block_size = 16
    max_len = max(256, prompt_len + max_tokens + 64)

    def eng(spec: str) -> LLMEngine:
        return LLMEngine(EngineConfig(
            model=model, dtype=dtype, max_num_seqs=4, max_model_len=max_len,
            block_size=block_size,
            num_blocks=max(256, 8 * (-(-max_len // block_size) + 4)),
            migration=1, fault_spec=spec, fault_seed=17,
        ), model_cfg=model_cfg, runner=runner)

    prompts, sampling = _pool_workload(model_cfg, n_requests, prompt_len,
                                       max_tokens)
    clean_pool = EnginePool([eng(""), eng("")], policy="round_robin")
    clean = _drive_pool(clean_pool, prompts, sampling,
                        step_cap=400 * n_requests)
    chaos_pool = EnginePool([eng("dispatch_error:p=0.15"), eng("")],
                            policy="round_robin")
    chaos = _drive_pool(chaos_pool, prompts, sampling,
                        step_cap=400 * n_requests)
    migrated = sum(v for (t, s), v in chaos_pool.migrations.items()
                   if s == "adopted")
    identical = all(chaos["outputs"][rid] == clean["outputs"].get(rid)
                    for rid in chaos["outputs"])
    return {
        "mode": "migration_soak",
        "requests": n_requests,
        "clean_completed": clean["completed"],
        "chaos_completed": chaos["completed"],
        "chaos_errored": chaos["errored"],
        "migrations_adopted": migrated,
        "migrations": {f"{t}:{s}": v
                       for (t, s), v in chaos_pool.migrations.items()},
        "all_terminated": clean["all_terminated"] and chaos["all_terminated"],
        "migrated_identical": identical,
    }


def run_scale_churn(*, runner, model_cfg, model: str, dtype: str,
                    n_requests: int, prompt_len: int,
                    max_tokens: int) -> dict:
    """Round-11 scale-churn soak: the clean workload runs on a fixed
    2-replica pool, then again under scale_to oscillation (2 → 3 → 1 → 2
    mid-traffic; scale-downs drain-and-migrate live streams). Gates:
    every stream terminates, completions are byte-identical to the fixed
    run, and the pool lands on the scripted final size."""
    from agentic_traffic_testing_tpu.runtime.engine import (
        EngineConfig,
        LLMEngine,
    )
    from agentic_traffic_testing_tpu.serving.replica_pool import EnginePool

    block_size = 16
    max_len = max(256, prompt_len + max_tokens + 64)

    def factory(i: int) -> LLMEngine:
        return LLMEngine(EngineConfig(
            model=model, dtype=dtype, max_num_seqs=4, max_model_len=max_len,
            block_size=block_size,
            num_blocks=max(256, 8 * (-(-max_len // block_size) + 4)),
            migration=1,
        ), model_cfg=model_cfg, runner=runner)

    prompts, sampling = _pool_workload(model_cfg, n_requests, prompt_len,
                                       max_tokens)
    clean = _drive_pool(EnginePool.build(factory, 2), prompts, sampling,
                        step_cap=400 * n_requests)
    pool = EnginePool.build(factory, 2)
    churn = _drive_pool(pool, prompts, sampling,
                        step_cap=400 * n_requests,
                        scale_script={2: 3, 5: 1, 9: 2})
    identical = all(churn["outputs"][rid] == clean["outputs"].get(rid)
                    for rid in churn["outputs"])
    return {
        "mode": "scale_churn",
        "requests": n_requests,
        "clean_completed": clean["completed"],
        "churn_completed": churn["completed"],
        "scale_events": pool.scale_events,
        "final_size": len(pool),
        "migrations": {f"{t}:{s}": v
                       for (t, s), v in pool.migrations.items()},
        "all_terminated": clean["all_terminated"] and churn["all_terminated"],
        "churn_identical": identical,
    }


def main(argv=None) -> list[dict]:
    argv = [int(a) for a in (argv if argv is not None else sys.argv[1:])]
    n_requests = argv[0] if len(argv) > 0 else 8
    prompt_len = argv[1] if len(argv) > 1 else 24
    max_tokens = argv[2] if len(argv) > 2 else 10

    import jax
    import jax.numpy as jnp

    from agentic_traffic_testing_tpu.models.config import resolve_config
    from agentic_traffic_testing_tpu.models.llama import init_params
    from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

    platform = jax.devices()[0].platform
    model = os.environ.get(
        "CHAOS_AB_MODEL", "llama-3.2-1b" if platform == "tpu" else "tiny")
    dtype = "bfloat16" if platform == "tpu" else "float32"
    seats = int(os.environ.get(
        "CHAOS_AB_SEATS", "16" if platform == "tpu" else "4"))
    fault_spec = os.environ.get("CHAOS_AB_FAULT_SPEC",
                                "dispatch_error:p=0.05")
    model_cfg = resolve_config(model)
    params = init_params(
        model_cfg, jax.random.key(0),
        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    runner = ModelRunner(model_cfg, params,
                         decode_steps=1 if platform != "tpu" else 16)
    print(f"devices: {jax.devices()}  requests={n_requests} seats={seats} "
          f"model={model} spec={fault_spec!r}", file=sys.stderr, flush=True)

    common = dict(runner=runner, model_cfg=model_cfg, model=model,
                  dtype=dtype, seats=seats, n_requests=n_requests,
                  prompt_len=prompt_len, max_tokens=max_tokens,
                  fault_spec=fault_spec)
    results = [run_arm(chaos, **common) for chaos in (False, True)]
    clean_out = results[0].pop("outputs")
    chaos_out = results[1].pop("outputs")
    # Identity gate: every request that COMPLETED under chaos matches the
    # clean arm's stream exactly (failing batches must not perturb
    # survivors — per-lane sampling keys make recompute deterministic).
    identical = all(chaos_out[i] == clean_out.get(i) for i in chaos_out)
    for r in results:
        r["unaffected_identical"] = identical
        print(json.dumps(r), flush=True)
    restore = run_restore_section(runner=runner, model_cfg=model_cfg,
                                  model=model, dtype=dtype)
    print(json.dumps(restore), flush=True)
    results.append(restore)
    soak_common = dict(runner=runner, model_cfg=model_cfg, model=model,
                       dtype=dtype, n_requests=n_requests,
                       prompt_len=prompt_len, max_tokens=max_tokens)
    for section in (run_migration_soak, run_scale_churn):
        r = section(**soak_common)
        print(json.dumps(r), flush=True)
        results.append(r)
    return results


if __name__ == "__main__":
    main()
