#!/usr/bin/env python3
"""Run the statics plane: every AST invariant checker, one JSON report.

The seven checkers (agentic_traffic_testing_tpu/statics/):

  knobs         every LLM_*/ATT_*/BENCH_* env read is registered in
                statics/knob_registry.py, no registry entry is dead, and
                docs/knobs.md matches the registry
  capabilities  supports_* flags resolve consistently across runner
                classes, every False flag has a build-time refusal
                guard, and docs/capabilities.md matches the declarations
  host-sync     no blocking host<->device synchronization inside the
                marked hot regions of engine.py/runner.py
  donation      no caller reads a buffer after donating it to a runner
                dispatch
  concurrency   thread-ownership lint + lock discipline for the serving
                plane (thread-context markers, attribute ownership vs
                statics/ownership_registry.py, lock-order cycles,
                blocking-under-lock, await-under-threading-lock,
                docs/threading.md parity)
  metric-docs   Prometheus families <-> docs/monitoring.md parity
                (scripts/dev/check_metric_docs.py behind a thin shim)
  kernelcontract
                every pl.pallas_call under ops/pallas/ honors its
                declared launch contract (statics/kernel_registry.py):
                dtype-legal tile shapes, kernel-body arity matching the
                spec lists, aliasing pairs that agree and are donated,
                justified "parallel" grid semantics, and a per-grid-step
                VMEM working set inside the per-generation budget table;
                docs/kernels.md matches the registry render

Usage:
  python scripts/dev/statics_all.py              # check; JSON report
  python scripts/dev/statics_all.py --write-docs # regenerate the
                                                 # generated docs first
  python scripts/dev/statics_all.py --only concurrency   # one checker

The report carries per-checker `wall_time_s` so CI can spot a checker
whose scan cost regressed.

Exit 0 when every checker is clean (all findings either fixed or
pragma'd with `# statics: allow-<rule>(<reason>)`), 1 otherwise.
Wired into tests/test_scripts.py as a default-tier smoke, so tier-1
fails on any new unregistered knob, missing guard, hot-region sync,
post-donation read, unowned cross-thread write, lock-discipline
violation, or matrix/doc drift.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--write-docs", action="store_true",
                   help="regenerate docs/knobs.md, docs/capabilities.md, "
                        "docs/threading.md + docs/kernels.md from their "
                        "source-of-truth surfaces before checking")
    p.add_argument("--quiet", action="store_true",
                   help="suppress the JSON report; exit code only")
    p.add_argument("--only", action="append", metavar="CHECKER",
                   help="run only this checker (repeatable); names are "
                        "the report keys (knobs, capabilities, "
                        "host-sync, donation, concurrency, metric-docs, "
                        "kernelcontract)")
    a = p.parse_args(argv)

    from agentic_traffic_testing_tpu.statics import run_all, write_docs

    if a.write_docs:
        for rel in write_docs(REPO):
            print(f"wrote {rel}", file=sys.stderr)
    try:
        report = run_all(REPO, only=a.only)
    except ValueError as exc:   # unknown --only name
        print(str(exc), file=sys.stderr)
        return 2
    if not a.quiet:
        print(json.dumps(report, indent=2))
    if not report["ok"]:
        total = sum(len(c["findings"]) for c in report["checkers"].values())
        print(f"statics: {total} finding(s) — see report above "
              f"(pragma syntax: # statics: allow-<rule>(<reason>))",
              file=sys.stderr)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
