#!/usr/bin/env bash
# Round-5 TPU tunnel watcher.
#
# The round-3/4 tunnel outages meant two consecutive rounds shipped with no
# driver-verified TPU perf artifact (VERDICT r4 "What's missing" #1).  This
# daemon closes the window-miss failure mode: it probes the tunnel every
# PROBE_INTERVAL seconds and, the moment a chip answers, runs the full
# validation + sweep batch and records timestamped artifacts under docs/
# (docs/bench_sweep_r4.jsonl rows + a docs/bench_watcher_*.json driver-
# semantics line) for BENCHMARKS.md and the round record.
#
# Usage:  nohup bash scripts/dev/tpu_watcher.sh & disown
# Stop:   touch scripts/dev/tpu_watcher.stop
set -u
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
cd "$REPO"
LOG=docs/tpu_watcher_r5.log
STOP=scripts/dev/tpu_watcher.stop
PROBE_INTERVAL="${PROBE_INTERVAL:-240}"
PROBE_TIMEOUT="${PROBE_TIMEOUT:-150}"

log() { echo "[$(date -u +%FT%TZ)] $*" >>"$LOG"; }

log "watcher start pid=$$ interval=${PROBE_INTERVAL}s"
while true; do
  if [ -e "$STOP" ]; then log "stop marker seen; exiting"; exit 0; fi
  if timeout "$PROBE_TIMEOUT" python scripts/dev/probe_tpu.py >>"$LOG" 2>&1; then
    TS=$(date -u +%Y%m%dT%H%M%SZ)
    log "TUNNEL UP — running validation + sweep (ts=$TS)"
    timeout 5400 python scripts/dev/tpu_r4_validation.py --sweep \
      >"docs/tpu_r5_validation_${TS}.log" 2>&1
    RC=$?
    log "validation+sweep rc=$RC (docs/tpu_r5_validation_${TS}.log)"
    # A standalone driver-semantics bench line too, in case the sweep died
    # partway: bench.py emits the one-line JSON the driver records.
    timeout 2400 python bench.py >"docs/bench_watcher_${TS}.json" 2>>"$LOG"
    log "bench rc=$? (docs/bench_watcher_${TS}.json)"
    log "watcher done; exiting so results are not overwritten"
    exit 0
  else
    log "probe: no device in ${PROBE_TIMEOUT}s"
  fi
  sleep "$PROBE_INTERVAL"
done
