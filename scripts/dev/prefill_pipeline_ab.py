#!/usr/bin/env python3
"""Pipelined-prefill A/B: dispatch overlap on/off x tuned/untuned blocks.

The engine-level A/B for the round-6 prefill claims, isolated from the
HTTP layer: a solo long-prompt prefill (the prefill_est_mfu=0.13 shape
ROADMAP flags) measured with the single blocking dispatch (`serial`) vs
K back-to-back position-chunk dispatches with one tail readback
(`pipeline`, LLM_PREFILL_PIPELINE) — and, when PIPELINE_AB_TUNE is set
(`warmup` or a table path), each arm repeated with ATT_FLASH_TUNE engaged
so the flash-block autotuner's contribution separates from the overlap's.
One JSON line per arm:

    {"mode": "serial"|"pipeline", "tune": "off"|..., "prefill_ttft_s": ...,
     "prefill_toks_s": ..., "pipeline_dispatches": N, "outputs_match": true}

`outputs_match` asserts every arm's completion is token-identical (the
correctness half of the claim; the engine suite additionally pins page
bytes — tests/test_prefill_pipeline.py). Each arm builds a FRESH runner:
block sizes and the pipeline program bake in at trace time, so arms must
not share compiled programs. Numbers feed docs/BENCHMARKS.md once measured
on hardware.

Usage: python scripts/dev/prefill_pipeline_ab.py [prompt_len] [chunks] [max_tokens]
Env: PIPELINE_AB_MODEL (default: tiny fp32 on cpu, llama-3.2-1b bf16 on tpu),
     PIPELINE_AB_TUNE (unset = untuned arms only).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def run_arm(pipeline_chunks: int, tune: str, *, model_cfg, params, model: str,
            dtype: str, prompt_len: int, max_tokens: int, reps: int) -> dict:
    import numpy as np

    from agentic_traffic_testing_tpu.ops.pallas import autotune
    from agentic_traffic_testing_tpu.runtime.engine import (
        EngineConfig,
        LLMEngine,
    )
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams
    from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

    prev = os.environ.get("ATT_FLASH_TUNE")
    if tune == "off":
        os.environ.pop("ATT_FLASH_TUNE", None)
    else:
        os.environ["ATT_FLASH_TUNE"] = tune
    autotune.reset()
    try:
        block_size = 16
        max_len = max(256, prompt_len + max_tokens + 64)
        eng = LLMEngine(EngineConfig(
            model=model, dtype=dtype, max_num_seqs=2, max_model_len=max_len,
            block_size=block_size,
            num_blocks=2 * (-(-max_len // block_size) + 4),
            prefill_pipeline_chunks=pipeline_chunks,
        ), model_cfg=model_cfg,
            runner=ModelRunner(model_cfg, params, decode_steps=1))

        wl = np.random.default_rng(17)  # reseeded per arm: identical workload
        vocab = model_cfg.vocab_size
        prompt = wl.integers(10, vocab - 10, prompt_len).tolist()
        sp = lambda: SamplingParams(temperature=0.0, max_tokens=max_tokens,
                                    ignore_eos=True)
        eng.generate(prompt, sp())  # warmup: pay every compile outside timing
        ttfts = []
        req = None
        for _ in range(reps):
            req = eng.generate(prompt, sp())
            ttfts.append(req.first_token_time - req.arrival_time)
        ttft = statistics.median(ttfts)
        return {
            "mode": "pipeline" if pipeline_chunks >= 2 else "serial",
            "tune": tune,
            "prompt_tokens": prompt_len,
            "pipeline_chunks": pipeline_chunks,
            "prefill_ttft_s": round(ttft, 4),
            "prefill_toks_s": round(prompt_len / ttft, 1),
            "pipeline_dispatches": eng.num_pipeline_dispatches,
            "outputs": req.generated_ids,
        }
    finally:
        if prev is None:
            os.environ.pop("ATT_FLASH_TUNE", None)
        else:
            os.environ["ATT_FLASH_TUNE"] = prev
        autotune.reset()


def main(argv=None) -> list[dict]:
    argv = [int(a) for a in (argv if argv is not None else sys.argv[1:])]
    prompt_len = argv[0] if len(argv) > 0 else 2048
    chunks = argv[1] if len(argv) > 1 else 4
    max_tokens = argv[2] if len(argv) > 2 else 4

    import jax
    import jax.numpy as jnp

    from agentic_traffic_testing_tpu.models.config import resolve_config
    from agentic_traffic_testing_tpu.models.llama import init_params

    platform = jax.devices()[0].platform
    model = os.environ.get(
        "PIPELINE_AB_MODEL", "llama-3.2-1b" if platform == "tpu" else "tiny")
    dtype = "bfloat16" if platform == "tpu" else "float32"
    reps = 3 if platform == "tpu" else 1
    tune = os.environ.get("PIPELINE_AB_TUNE")
    model_cfg = resolve_config(model)
    params = init_params(
        model_cfg, jax.random.key(0),
        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    print(f"devices: {jax.devices()}  prompt={prompt_len} chunks={chunks} "
          f"model={model} tune={tune or 'off'}", file=sys.stderr, flush=True)

    common = dict(model_cfg=model_cfg, params=params, model=model,
                  dtype=dtype, prompt_len=prompt_len, max_tokens=max_tokens,
                  reps=reps)
    arms = [(0, "off"), (chunks, "off")]
    if tune:
        arms += [(0, tune), (chunks, tune)]
    results = [run_arm(k, tn, **common) for k, tn in arms]
    # Correctness gate: every arm must produce the identical completion.
    outs = {tuple(r["outputs"]) for r in results}
    for r in results:
        r["outputs_match"] = len(outs) == 1
        r.pop("outputs")
        print(json.dumps(r), flush=True)
    return results


if __name__ == "__main__":
    main()
