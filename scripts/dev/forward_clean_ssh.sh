#!/usr/bin/env bash
# Forward the testbed's UI/observability ports from a remote host over SSH,
# killing any stale forwards first (reference: scripts/dev/forward_clean_ssh.sh).
#
# Usage: forward_clean_ssh.sh <user@host> [extra ssh args...]
set -euo pipefail

[ $# -ge 1 ] || { echo "usage: $0 <user@host> [ssh args...]" >&2; exit 2; }
TARGET="$1"; shift

# UI 3000, Grafana 3001, Prometheus 9090, Jaeger 16686, agent-a 8101, LLM 8000.
PORTS=(3000 3001 9090 16686 8101 8000)

# Kill stale forwards for these ports (previous runs that lost their TTY).
for p in "${PORTS[@]}"; do
  pids=$(pgrep -f "ssh .*-L ${p}:localhost:${p}" || true)
  [ -n "$pids" ] && { echo "[dev] killing stale forward for :$p ($pids)"; kill $pids || true; }
done

ARGS=()
for p in "${PORTS[@]}"; do ARGS+=(-L "${p}:localhost:${p}"); done

echo "[dev] forwarding ${PORTS[*]} from $TARGET (Ctrl-C to stop)"
exec ssh -N -o ServerAliveInterval=30 -o ExitOnForwardFailure=yes \
  "${ARGS[@]}" "$@" "$TARGET"
