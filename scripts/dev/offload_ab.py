#!/usr/bin/env python3
"""Host-KV-offload A/B on the recurring-scenario workload.

The engine-level A/B for the tiered-KV-cache claim (runtime/kv_offload.py),
isolated from the HTTP layer: a scenario prompt is computed once, evicted
from the device prefix cache by capacity pressure (a KV pool deliberately
too small to retain it), then re-requested. With the host tier ON the
re-arrival restores the prefix host→device and prefills only the suffix;
OFF it pays the full prefill recompute — the exact hot path ROADMAP flags
(prefill MFU 0.13 makes recompute expensive; host restore is a memcpy-
shaped stream). One JSON line per mode:

    {"mode": "offload"|"recompute", "rearrival_ttft_s": ...,
     "host_hit_tokens": ..., "restore_bytes": ..., "restore_gb_s": ...,
     "outputs_match": true}

`outputs_match` asserts the restored completion is byte-identical to the
recompute completion (the correctness half of the claim). Numbers feed
docs/BENCHMARKS.md once measured on hardware.

Usage: python scripts/dev/offload_ab.py [prefix_len] [pressure_prompts] [host_mb]
Env: OFFLOAD_AB_MODEL (default: tiny fp32 on cpu, llama-3.2-1b bf16 on tpu).
No reference analog (the reference's vLLM tier is device-only).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def run_mode(host_mb: float, *, runner, model_cfg, model: str, dtype: str,
             prefix_len: int, pressure: int, reps: int) -> dict:
    import numpy as np

    from agentic_traffic_testing_tpu.runtime.engine import (
        EngineConfig,
        LLMEngine,
    )
    from agentic_traffic_testing_tpu.runtime.kv_offload import HostKVStore
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams

    block_size = 16
    max_len = prefix_len + 96
    # Pool sized to ONE scenario footprint plus a little slack: requests
    # run one at a time, so every pressure prompt after the first must dig
    # into the evictable LRU — guaranteed reclaim of the scenario's blocks
    # (and, with the tier ON, guaranteed device→host spills).
    num_blocks = (-(-(prefix_len + 32) // block_size) + 3) + 1
    store = HostKVStore(int(host_mb * 1e6)) if host_mb > 0 else None
    eng = LLMEngine(EngineConfig(
        model=model, dtype=dtype, max_num_seqs=2, max_model_len=max_len,
        block_size=block_size, num_blocks=num_blocks, prefix_caching=True,
    ), model_cfg=model_cfg, runner=runner, host_store=store)

    wl = np.random.default_rng(11)  # reseeded per mode: identical workload
    vocab = model_cfg.vocab_size
    scenario = wl.integers(10, vocab - 10, prefix_len).tolist()
    pressures = [wl.integers(10, vocab - 10, prefix_len).tolist()
                 for _ in range(pressure)]
    sp = lambda: SamplingParams(temperature=0.0, max_tokens=8,
                                ignore_eos=True)

    first = eng.generate(scenario, sp())
    ttfts = []
    for _ in range(reps):
        for p in pressures:  # evict the scenario's blocks (spilling if ON)
            eng.generate(p, sp())
        re_req = eng.generate(scenario, sp())
        ttfts.append(re_req.first_token_time - re_req.arrival_time)
    stats = eng.kv_stats()
    ttft = statistics.median(ttfts)
    restore_bytes = int(stats.get("host_cache_restore_bytes", 0))
    return {
        "mode": "offload" if store is not None else "recompute",
        "prefix_tokens": prefix_len,
        "pressure_prompts": pressure,
        "rearrival_ttft_s": round(ttft, 4),
        "host_hit_tokens": int(stats.get("host_cache_hit_tokens", 0)),
        "restore_bytes": restore_bytes,
        "restore_gb_s": (round(restore_bytes / max(sum(ttfts), 1e-9) / 1e9, 3)
                         if restore_bytes else 0.0),
        "outputs": re_req.generated_ids,
        "first_outputs": first.generated_ids,
    }


def main(argv=None) -> list[dict]:
    argv = [float(a) for a in (argv if argv is not None else sys.argv[1:])]
    prefix_len = int(argv[0]) if len(argv) > 0 else 128
    pressure = int(argv[1]) if len(argv) > 1 else 3
    host_mb = argv[2] if len(argv) > 2 else 256.0

    import jax
    import jax.numpy as jnp

    from agentic_traffic_testing_tpu.models.config import resolve_config
    from agentic_traffic_testing_tpu.models.llama import init_params
    from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

    platform = jax.devices()[0].platform
    model = os.environ.get(
        "OFFLOAD_AB_MODEL", "llama-3.2-1b" if platform == "tpu" else "tiny")
    dtype = "bfloat16" if platform == "tpu" else "float32"
    reps = 3 if platform == "tpu" else 1
    model_cfg = resolve_config(model)
    params = init_params(
        model_cfg, jax.random.key(0),
        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    runner = ModelRunner(model_cfg, params)
    print(f"devices: {jax.devices()}  prefix={prefix_len} "
          f"pressure={pressure} host_mb={host_mb} model={model}",
          file=sys.stderr, flush=True)

    common = dict(runner=runner, model_cfg=model_cfg, model=model,
                  dtype=dtype, prefix_len=prefix_len, pressure=pressure,
                  reps=reps)
    # Discarded warmup pass (tier ON, so the restore path's suffix-chunk
    # shapes compile too) — neither measured mode pays XLA compiles inside
    # its TTFT.
    run_mode(host_mb, **{**common, "reps": 1})
    results = []
    for mb in (host_mb, 0):
        results.append(run_mode(mb, **common))
    # Correctness gate: the restored completion must match the recompute
    # completion byte-for-byte (and the original computation).
    outs = {tuple(r["outputs"]) for r in results}
    outs |= {tuple(r["first_outputs"]) for r in results}
    for r in results:
        r["outputs_match"] = len(outs) == 1
        r.pop("outputs"), r.pop("first_outputs")
        print(json.dumps(r), flush=True)
    return results


if __name__ == "__main__":
    main()
