#!/usr/bin/env python3
"""Experiment: cheaper nibble-unpack strategies for the int4 matmul kernel.

Round-5 finding (scripts/dev/quant_ab.py on chip): the int4 kernel runs at
~340-360 GB/s effective vs the XLA int8 matmul's ~700 — the kernel is
VPU-unpack-bound, not HBM-bound, so int4's halved bytes buy nothing over
int8 at the 8B shapes. Each variant here is a minimal standalone kernel
over one [K, half] packed block (the real kernel's inner loop) so the
unpack strategy is the only difference:

  v0_shift32  — the shipping unpack: i8->i32 widen, shl/shr sign
                extension, two i32->bf16 casts (6 VPU passes).
  v1_bitcast4 — pltpu bitcast / lax.bitcast_convert_type to native s4,
                then one s4->bf16 cast per half (2 passes) — IF Mosaic
                legalizes s4 casts.
  v2_sub      — hi = w >> 4 (2 ops incl widen), lo = w - 16*hi (2 ops,
                no second shift chain), two casts (6 passes, different
                mix — measures whether shifts or casts dominate).
  v3_byte     — signed-byte identity b = 16*(b>>4) + (b&15): dot x@byte
                and x@lo_u, recover y_hi = (y_byte - y_lo_u)/16 on the
                f32 accumulators. lo still needs its signed unpack; hi
                unpack vanishes (4 passes + 1 extra f32 AXPY on [B,hb]).

Each prints device ms/call and effective GB/s on the packed bytes.
Usage: python scripts/dev/int4_unpack_ab.py [K] [HALF] [B]
"""

from __future__ import annotations

import functools
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from scripts.dev.quant_ab import device_total_ms

N = 8


def _v0(x_ref, w_ref, lo_out, hi_out):
    w32 = w_ref[...].astype(jnp.int32)
    lo = jax.lax.shift_right_arithmetic(
        jax.lax.shift_left(w32, jnp.int32(28)), jnp.int32(28))
    hi = jax.lax.shift_right_arithmetic(w32, jnp.int32(4))
    x = x_ref[...]
    dims = (((1,), (0,)), ((), ()))
    ye = jax.lax.dot_general(x, lo.astype(x.dtype), dims,
                             preferred_element_type=jnp.float32)
    yo = jax.lax.dot_general(x, hi.astype(x.dtype), dims,
                             preferred_element_type=jnp.float32)
    lo_out[...] = ye.astype(jnp.bfloat16)
    hi_out[...] = yo.astype(jnp.bfloat16)


def _v1(x_ref, w_ref, lo_out, hi_out):
    w4 = jax.lax.bitcast_convert_type(w_ref[...], jnp.int4)  # [K, half, 2]
    lo = w4[..., 0].astype(jnp.bfloat16)
    hi = w4[..., 1].astype(jnp.bfloat16)
    x = x_ref[...]
    dims = (((1,), (0,)), ((), ()))
    ye = jax.lax.dot_general(x, lo, dims,
                             preferred_element_type=jnp.float32)
    yo = jax.lax.dot_general(x, hi, dims,
                             preferred_element_type=jnp.float32)
    lo_out[...] = ye.astype(jnp.bfloat16)
    hi_out[...] = yo.astype(jnp.bfloat16)


def _v2(x_ref, w_ref, lo_out, hi_out):
    # hi via one shift; SIGNED lo via subtract of the unsigned nibble's
    # sign bit (lo_u - 16*(lo_u >= 8)) — swaps v0's shl/shr chain for
    # and/cmp/sub, same pass count, measures op-mix sensitivity.
    w32 = w_ref[...].astype(jnp.int32)
    hi = jax.lax.shift_right_arithmetic(w32, jnp.int32(4))
    lo_u = w32 & jnp.int32(15)
    lo = lo_u - jnp.where(lo_u >= 8, jnp.int32(16), jnp.int32(0))
    x = x_ref[...]
    dims = (((1,), (0,)), ((), ()))
    ye = jax.lax.dot_general(x, lo.astype(x.dtype), dims,
                             preferred_element_type=jnp.float32)
    yo = jax.lax.dot_general(x, hi.astype(x.dtype), dims,
                             preferred_element_type=jnp.float32)
    lo_out[...] = ye.astype(jnp.bfloat16)
    hi_out[...] = yo.astype(jnp.bfloat16)


def _v3(x_ref, w_ref, lo_out, hi_out):
    w8 = w_ref[...]
    w32 = w8.astype(jnp.int32)
    lo = jax.lax.shift_right_arithmetic(
        jax.lax.shift_left(w32, jnp.int32(28)), jnp.int32(28))
    lo_u = lo & jnp.int32(15)            # unsigned low nibble, cheap from lo
    x = x_ref[...]
    dims = (((1,), (0,)), ((), ()))
    y_lo = jax.lax.dot_general(x, lo.astype(x.dtype), dims,
                               preferred_element_type=jnp.float32)
    y_lo_u = jax.lax.dot_general(x, lo_u.astype(x.dtype), dims,
                                 preferred_element_type=jnp.float32)
    y_byte = jax.lax.dot_general(x, w8.astype(x.dtype), dims,
                                 preferred_element_type=jnp.float32)
    yo = (y_byte - y_lo_u) * jnp.float32(1 / 16)
    lo_out[...] = y_lo.astype(jnp.bfloat16)
    hi_out[...] = yo.astype(jnp.bfloat16)


def _v4(x_ref, w_ref, lo_out, hi_out):
    # One concatenated dot: unpack as v0 but stack [lo | hi] into a single
    # [K, 2*half] operand so the MXU runs once — measures dot-setup cost.
    w32 = w_ref[...].astype(jnp.int32)
    lo = jax.lax.shift_right_arithmetic(
        jax.lax.shift_left(w32, jnp.int32(28)), jnp.int32(28))
    hi = jax.lax.shift_right_arithmetic(w32, jnp.int32(4))
    w_all = jnp.concatenate([lo, hi], axis=1).astype(jnp.bfloat16)
    x = x_ref[...]
    dims = (((1,), (0,)), ((), ()))
    y = jax.lax.dot_general(x, w_all, dims,
                            preferred_element_type=jnp.float32)
    half = lo.shape[1]
    lo_out[...] = y[:, :half].astype(jnp.bfloat16)
    hi_out[...] = y[:, half:].astype(jnp.bfloat16)


def _v5(x_ref, w_ref, lo_out, hi_out):
    # BIASED-lo packing simulation (b' = b + 8 = 16*hi + (lo+8)): unpack is
    # one i8 AND + two direct i8->bf16 casts; y_lo/y_hi recovered from the
    # byte dot and the biased-lo dot in the f32 epilogue plus a rank-0
    # rowsum correction. Operand here is the SAME random int8 block — the
    # variant reads w as if packed biased, so outputs differ from v0 by
    # the simulated bias (accuracy checked separately; this measures ops).
    w8 = w_ref[...]
    lo_b = (w8 & jnp.int8(15)).astype(jnp.bfloat16)      # [K, half]
    byte = w8.astype(jnp.bfloat16)
    x = x_ref[...]
    dims = (((1,), (0,)), ((), ()))
    y_lo_b = jax.lax.dot_general(x, lo_b, dims,
                                 preferred_element_type=jnp.float32)
    y_byte = jax.lax.dot_general(x, byte, dims,
                                 preferred_element_type=jnp.float32)
    rowsum = jnp.sum(x.astype(jnp.float32), axis=1, keepdims=True)
    lo_out[...] = (y_lo_b - 8.0 * rowsum).astype(jnp.bfloat16)
    hi_out[...] = ((y_byte - y_lo_b) * jnp.float32(1 / 16)).astype(
        jnp.bfloat16)


def build(kernel, k, half, b):
    f = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((b, half), jnp.bfloat16),
                   jax.ShapeDtypeStruct((b, half), jnp.bfloat16)],
    )
    return jax.jit(lambda x, w: f(x, w))


def main():
    argv = [int(a) for a in sys.argv[1:]]
    k = argv[0] if len(argv) > 0 else 4096
    half = argv[1] if len(argv) > 1 else 256
    b = argv[2] if len(argv) > 2 else 32
    print(f"devices: {jax.devices()}  K={k} half={half} B={b}", flush=True)
    xs = [(jax.random.normal(jax.random.key(2 * i), (b, k), jnp.bfloat16),
           jax.random.randint(jax.random.key(2 * i + 1), (k, half),
                              -128, 128, jnp.int8))
          for i in range(N)]
    byts = k * half
    ref = None
    for name, kern in (("v0_shift32", _v0), ("v1_bitcast4", _v1),
                       ("v2_sub", _v2), ("v3_byte", _v3),
                       ("v4_onedot", _v4), ("v5_biased", _v5)):
        check = name != "v5_biased"   # v5 simulates a different packing
        try:
            fn = build(kern, k, half, b)
            lo, hi = fn(*xs[0])
            if ref is None:
                ref = (lo, hi)
            elif not check:
                pass
            else:
                scale_ref = float(jnp.max(jnp.abs(ref[0].astype(jnp.float32)))
                                  + jnp.max(jnp.abs(ref[1]
                                                    .astype(jnp.float32))))
                dl = float(jnp.max(jnp.abs(lo.astype(jnp.float32)
                                           - ref[0].astype(jnp.float32))))
                dh = float(jnp.max(jnp.abs(hi.astype(jnp.float32)
                                           - ref[1].astype(jnp.float32))))
                # bf16 outputs at magnitude ~scale_ref quantize to
                # ~scale/256 steps; allow a few ulps of f32-accum skew.
                if max(dl, dh) > scale_ref / 64:
                    print(f"  {name:<12s} WRONG (max dev {max(dl, dh):.3f} "
                          f"at scale {scale_ref:.1f})", flush=True)
                    continue
            ms = device_total_ms(fn, xs, f"/tmp/int4_ab_{name}")
            print(f"  {name:<12s} {ms * 1e3:8.1f} us/call DEVICE "
                  f"({byts / (ms / 1e3) / 1e9:5.0f} GB/s eff)", flush=True)
        except Exception as e:  # noqa: BLE001 — experiment harness
            print(f"  {name:<12s} FAILED: {type(e).__name__}: "
                  f"{str(e).splitlines()[0][:120]}", flush=True)


if __name__ == "__main__":
    main()
