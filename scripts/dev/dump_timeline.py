#!/usr/bin/env python3
"""Dump a step-clock Chrome trace (runtime/telemetry.py) to JSON.

Two modes:

  * in-process (default): build a tiny engine with the step-trace plane
    on, run a small mixed workload (batched prefill + decode + one abort
    so the timeline shows real churn), and write the merged
    `{"traceEvents": [...]}` document — the zero-setup way to see what
    the recorder captures. Load the file at ui.perfetto.dev or
    chrome://tracing: one track is the engine step clock (dispatch/drain
    slices), one track per request shows its queued/prefill/decode spans.
  * --url http://host:8000 : fetch a LIVE server's `GET /debug/timeline`
    instead (the server must run with LLM_STEP_TRACE=1).

Usage: python scripts/dev/dump_timeline.py [out.json] [n_requests] [max_tokens]
Env: TIMELINE_MODEL (default: tiny fp32 on cpu, llama-3.2-1b bf16 on tpu).

Exits non-zero if the dumped document fails the trace-event schema check
(every event carries ph/pid/tid, every X slice ts+dur) — the same check
tests/test_scripts.py smokes.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def validate_trace(doc: dict) -> None:
    """Assert the minimal Chrome trace-event schema Perfetto needs."""
    events = doc.get("traceEvents")
    assert isinstance(events, list) and events, "empty traceEvents"
    for e in events:
        assert e.get("ph") in ("X", "i", "M"), f"bad ph in {e}"
        assert "pid" in e and "tid" in e, f"missing pid/tid in {e}"
        if e["ph"] in ("X", "i"):
            assert isinstance(e.get("ts"), (int, float)), f"missing ts in {e}"
        if e["ph"] == "X":
            assert isinstance(e.get("dur"), (int, float)), f"missing dur in {e}"
    json.dumps(doc)  # must be serializable as-is


def fetch_live(url: str) -> dict:
    import urllib.request

    with urllib.request.urlopen(f"{url.rstrip('/')}/debug/timeline",
                                timeout=30) as resp:
        return json.loads(resp.read())


def run_local(n_requests: int, max_tokens: int) -> dict:
    import jax
    import numpy as np

    from agentic_traffic_testing_tpu.runtime.engine import (
        EngineConfig,
        LLMEngine,
    )
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams
    from agentic_traffic_testing_tpu.runtime.telemetry import (
        chrome_trace_document,
    )

    platform = jax.devices()[0].platform
    model = os.environ.get("TIMELINE_MODEL") or (
        "llama-3.2-1b" if platform == "tpu" else "tiny")
    dtype = "bfloat16" if platform == "tpu" else "float32"
    eng = LLMEngine(EngineConfig(
        model=model, dtype=dtype, max_num_seqs=max(4, n_requests),
        max_model_len=256, block_size=16, num_blocks=256,
        step_trace=1))
    rng = np.random.default_rng(0)
    vocab = eng.model_cfg.vocab_size
    reqs = [eng.add_request(
        rng.integers(10, vocab - 10, 16 + 2 * i).tolist(),
        SamplingParams(temperature=0.0, max_tokens=max_tokens,
                       ignore_eos=True))
        for i in range(n_requests)]
    # Abort one mid-flight so the dump shows a non-happy-path timeline.
    aborted = False
    for _ in range(10_000):
        eng.step()
        if not aborted and any(r.output_ids for r in reqs):
            eng.abort_request(reqs[-1])
            aborted = True
        if all(r.is_finished() for r in reqs):
            break
        if not eng.has_work():
            break
    return chrome_trace_document([eng.telemetry])


def main(argv=None) -> dict:
    argv = list(sys.argv[1:] if argv is None else argv)
    url = None
    if "--url" in argv:
        i = argv.index("--url")
        url = argv[i + 1]
        del argv[i:i + 2]
    out_path = argv[0] if len(argv) > 0 else "/tmp/step_clock_timeline.json"
    n_requests = int(argv[1]) if len(argv) > 1 else 3
    max_tokens = int(argv[2]) if len(argv) > 2 else 8
    doc = fetch_live(url) if url else run_local(n_requests, max_tokens)
    validate_trace(doc)
    with open(out_path, "w") as f:
        json.dump(doc, f)
    n_req_tracks = sum(1 for e in doc["traceEvents"]
                       if e.get("ph") == "M"
                       and e.get("name") == "thread_name"
                       and str(e.get("args", {}).get("name", "")).startswith("req "))
    print(json.dumps({
        "out": out_path,
        "events": len(doc["traceEvents"]),
        "request_tracks": n_req_tracks,
        "pids": sorted({e["pid"] for e in doc["traceEvents"]}),
    }))
    return doc


if __name__ == "__main__":
    main()
