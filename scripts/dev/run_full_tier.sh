#!/usr/bin/env bash
# Full test tier, module-serial, with a per-module record — the CI /
# round-certification gate (round-3 verdict item #6 / advisor medium).
#
# Why module-serial instead of one `pytest tests/ -m "full or not full"`:
# this box has one CPU core; a single 30+ minute pytest process gets killed
# by driver-side contention and loses everything, while a per-module loop
# survives partial completion and records what ran (round-3 lesson).
#
# Output: one line per module + a final count, and a JSON summary appended
# to ${FULL_TIER_RECORD:-/tmp/full_tier_record.jsonl} for the round
# artifacts.
set -u
cd "$(dirname "$0")/../.."

RECORD="${FULL_TIER_RECORD:-/tmp/full_tier_record.jsonl}"
total_passed=0; total_failed=0; failed_modules=()
start=$(date +%s)

for mod in tests/test_*.py; do
    t0=$(date +%s)
    out=$(python -m pytest "$mod" -m "full or not full" -q 2>&1)
    rc=$?
    out=$(echo "$out" | tail -3)
    line=$(echo "$out" | grep -Eo '[0-9]+ passed' | head -1)
    passed=${line%% *}; passed=${passed:-0}
    fline=$(echo "$out" | grep -Eo '[0-9]+ failed' | head -1)
    failed=${fline%% *}; failed=${failed:-0}
    total_passed=$((total_passed + passed))
    total_failed=$((total_failed + failed))
    # Any nonzero rc marks the module: rc=1 also covers 'N errors' runs
    # (fixture/setup exceptions) that print no 'failed' count at all.
    [ "$failed" != "0" ] || [ $rc -ne 0 ] && failed_modules+=("$mod")
    echo "[full-tier] $mod: ${passed} passed ${failed} failed ($(( $(date +%s) - t0 ))s)"
done

dur=$(( $(date +%s) - start ))
echo "[full-tier] TOTAL: ${total_passed} passed, ${total_failed} failed in ${dur}s"
printf '{"event":"full_tier","passed":%d,"failed":%d,"duration_s":%d,"failed_modules":"%s","date":"%s"}\n' \
    "$total_passed" "$total_failed" "$dur" "${failed_modules[*]:-}" "$(date -Is)" >> "$RECORD"
# Gate on failed_modules, not the parsed 'N failed' count: a module that
# dies at collection (rc=2, "1 error") or is killed mid-run never prints
# "N failed" and would otherwise leave the gate green with a suite unrun.
[ "${#failed_modules[@]}" -eq 0 ]
