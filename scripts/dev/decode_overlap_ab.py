#!/usr/bin/env python3
"""Overlapped-decode A/B: LLM_DECODE_OVERLAP on/off, engine-isolated.

The engine-level A/B for the round-7 decode claims, isolated from the
HTTP layer: a sustained multi-wave decode workload (the bs32
roofline_frac shape ROADMAP flags) measured with the serial per-dispatch
plan/table-rebuild loop (`serial`) vs the overlapped fast path
(`overlap`, LLM_DECODE_OVERLAP=1 — speculative next-step dispatch against
the predicted composition, incremental device-side table scatter, donated
DecodeState carry). One JSON line per arm:

    {"mode": "serial"|"overlap", "decode_toks_s": ...,
     "overlap_dispatches": N, "mispredicts": M, "outputs_match": true}

The workload deliberately churns: more requests than seats (admission
mid-decode), mixed greedy/seeded sampling, mixed max_tokens, and an EOS
stop token picked from a deterministic probe pass so some lanes stop
mid-dispatch — exercising exactly the mispredict reconciliation the
overlap path must get right. `outputs_match` asserts every arm's
completions are token-identical (the correctness half of the claim; the
engine suite additionally pins the serial path bit-identical —
tests/test_decode_overlap.py). Both arms share ONE ModelRunner: the
serial and overlapped decode programs are separate jits on the same
runner, so sharing compiles each exactly once without cross-arm state.
Numbers feed docs/BENCHMARKS.md once measured on hardware.

Usage: python scripts/dev/decode_overlap_ab.py [n_requests] [prompt_len] [max_tokens]
Env: OVERLAP_AB_MODEL (default: tiny fp32 on cpu, llama-3.2-1b bf16 on tpu),
     OVERLAP_AB_SEATS (default 4 on cpu, 32 on tpu).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def run_arm(overlap: int, *, runner, model_cfg, model: str, dtype: str,
            seats: int, n_requests: int, prompt_len: int, max_tokens: int,
            reps: int) -> dict:
    import numpy as np

    from agentic_traffic_testing_tpu.runtime.engine import (
        EngineConfig,
        LLMEngine,
    )
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams

    block_size = 16
    max_len = max(256, prompt_len + max_tokens + 64)
    eng = LLMEngine(EngineConfig(
        model=model, dtype=dtype, max_num_seqs=seats, max_model_len=max_len,
        block_size=block_size,
        num_blocks=max(256, seats * (-(-max_len // block_size) + 4)),
        decode_overlap=overlap,
    ), model_cfg=model_cfg, runner=runner)

    wl = np.random.default_rng(29)  # reseeded per arm: identical workload
    vocab = model_cfg.vocab_size
    prompts = [wl.integers(10, vocab - 10, prompt_len).tolist()
               for _ in range(n_requests)]

    # Deterministic probe: one greedy completion picks the EOS token the
    # churn wave will stop on — identical across arms by construction.
    probe = eng.generate(prompts[0], SamplingParams(
        temperature=0.0, max_tokens=max_tokens, ignore_eos=True))
    stop_tok = probe.output_ids[len(probe.output_ids) // 2]

    def sampling(i: int) -> SamplingParams:
        # Mixed stop lengths + mixed greedy/seeded + a reachable stop
        # token on the greedy lanes: stops land mid-dispatch, admissions
        # follow, and the overlap path must reconcile both.
        if i % 2 == 0:
            return SamplingParams(temperature=0.0,
                                  max_tokens=max_tokens - (i % 3),
                                  stop_token_ids=[stop_tok])
        return SamplingParams(temperature=0.8, top_k=20, seed=5 + i,
                              max_tokens=max_tokens // 2 + (i % 4),
                              ignore_eos=True)

    def wave():
        reqs = [eng.add_request(p, sampling(i))
                for i, p in enumerate(prompts)]
        t0 = time.monotonic()
        while eng.has_work() and not all(r.is_finished() for r in reqs):
            eng.step()
        dt = time.monotonic() - t0
        return reqs, sum(len(r.output_ids) for r in reqs) / dt

    wave()  # warmup: pay every compile outside timing
    vals = []
    reqs = None
    for _ in range(reps):
        reqs, toks_s = wave()
        vals.append(toks_s)
    return {
        "mode": "overlap" if overlap else "serial",
        "requests": n_requests,
        "seats": seats,
        "decode_toks_s": round(statistics.median(vals), 2),
        "overlap_dispatches": eng.num_overlap_dispatches,
        "mispredicts": eng.num_overlap_mispredicts,
        "outputs": [r.output_ids for r in reqs],
    }


def main(argv=None) -> list[dict]:
    argv = [int(a) for a in (argv if argv is not None else sys.argv[1:])]
    n_requests = argv[0] if len(argv) > 0 else 6
    prompt_len = argv[1] if len(argv) > 1 else 32
    max_tokens = argv[2] if len(argv) > 2 else 12

    import jax
    import jax.numpy as jnp

    from agentic_traffic_testing_tpu.models.config import resolve_config
    from agentic_traffic_testing_tpu.models.llama import init_params
    from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

    platform = jax.devices()[0].platform
    model = os.environ.get(
        "OVERLAP_AB_MODEL", "llama-3.2-1b" if platform == "tpu" else "tiny")
    dtype = "bfloat16" if platform == "tpu" else "float32"
    seats = int(os.environ.get(
        "OVERLAP_AB_SEATS", "32" if platform == "tpu" else "4"))
    reps = 3 if platform == "tpu" else 1
    model_cfg = resolve_config(model)
    params = init_params(
        model_cfg, jax.random.key(0),
        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    runner = ModelRunner(model_cfg, params, decode_steps=1 if platform != "tpu" else 32)
    print(f"devices: {jax.devices()}  requests={n_requests} seats={seats} "
          f"model={model}", file=sys.stderr, flush=True)

    common = dict(runner=runner, model_cfg=model_cfg, model=model,
                  dtype=dtype, seats=seats, n_requests=n_requests,
                  prompt_len=prompt_len, max_tokens=max_tokens, reps=reps)
    results = [run_arm(ov, **common) for ov in (0, 1)]
    # Correctness gate: both arms must produce identical completions.
    outs = {json.dumps(r["outputs"]) for r in results}
    for r in results:
        r["outputs_match"] = len(outs) == 1
        r.pop("outputs")
        print(json.dumps(r), flush=True)
    return results


if __name__ == "__main__":
    main()
