#!/usr/bin/env python3
"""A/B the solo-prefill attention implementations on the real chip.

DEVICE time per call via the shared xplane harness (wall clock through
the axon tunnel is unusable for kernels — see xplane_util docstring).
Round-5 result at T=2048 (1B GQA layout 32:8, hd=64, bf16): first-party
chunk_flash 0.41 ms/call vs library flash 0.54 — the in-tree kernel is
~25% faster on device; the 5.92 ms the r4 wall-clock probe reported was
tunnel serialization, not the kernel.

Usage: python scripts/dev/flash_ab.py [T ...]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
import jax.numpy as jnp

from scripts.dev.xplane_util import traced_device_ms
from agentic_traffic_testing_tpu.ops.flash_prefill import (
    _library_flash_attention,
)
from agentic_traffic_testing_tpu.ops.pallas.chunk_flash import (
    causal_flash_attention,
)

N = 8  # varied input sets per implementation


def main():
    shapes = [int(a) for a in sys.argv[1:]] or [2048]
    for t in shapes:
        print(f"T={t} B=1 H=32 KH=8 hd=64 bf16:", flush=True)
        args_list = [
            (jax.random.normal(jax.random.key(3 * i), (1, t, 32, 64),
                               jnp.bfloat16),
             jax.random.normal(jax.random.key(3 * i + 1), (1, t, 8, 64),
                               jnp.bfloat16),
             jax.random.normal(jax.random.key(3 * i + 2), (1, t, 8, 64),
                               jnp.bfloat16))
            for i in range(N)
        ]
        for name, fn, match, tdir in (
            ("first-party chunk_flash", jax.jit(causal_flash_attention),
             "causal_flash", "/tmp/flash_ab_fp"),
            ("library flash", jax.jit(_library_flash_attention),
             "flash_attention", "/tmp/flash_ab_lib"),
        ):
            ms = traced_device_ms(fn, args_list, match, tdir)
            print(f"  {name:<28s} {ms:8.3f} ms/call DEVICE", flush=True)


if __name__ == "__main__":
    main()
