#!/usr/bin/env python3
"""One-chip validation + measurement batch for the round-4 TPU-pending work.

Run FIRST when a real chip is reachable (the round-3/4 tunnel outages mean
several paths ship CPU/interpret-verified only):

  1. the round-3 batch (flash blocks, fp8 dma2, int4 K-group, fp8 engine,
     chunk-flash) via scripts/dev/tpu_r3_validation.py — unchanged debt,
  2. the round-4 FIRST-PARTY causal flash kernel (replaced the
     jax.experimental library kernel): correctness vs the jnp oracle at
     solo/batched/odd-bucket shapes on real Mosaic tiling, plus a timing
     probe against the round-3 library-kernel figure (~0.54 ms/layer at
     T=2048 on the 1B head layout — if the in-tree kernel is slower, run
     the block autotuner: ATT_FLASH_TUNE=warmup, ops/pallas/autotune.py),
  3. (--sweep) the verdict-item-3 batch-scaling sweep: bf16/int8/int4
     x bs {8,16,32} on the 1B and 8B + an fp8-KV row, by invoking
     bench.py per config and appending its JSON lines to
     docs/bench_sweep_r4.jsonl for BENCHMARKS.md.

Usage:  python scripts/dev/tpu_r4_validation.py [--sweep] [--skip-r3]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import traceback

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
sys.path.insert(0, REPO)

FAILED = []


def check(name):
    def deco(fn):
        def run():
            try:
                fn()
                print(f"PASS {name}", flush=True)
            except Exception:
                FAILED.append(name)
                print(f"FAIL {name}", flush=True)
                traceback.print_exc()
        return run
    return deco


@check("first-party causal flash kernel vs oracle on hardware")
def t_causal_flash():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from agentic_traffic_testing_tpu.ops.jnp_ops import causal_attention
    from agentic_traffic_testing_tpu.ops.pallas.chunk_flash import (
        causal_flash_attention,
    )

    # (B, T) covers: solo 2k (the headline prefill), batched fan-out
    # (5 x 512 — the TTFT probe's bucket), odd bucket 640 (pow2-divisor
    # fallback), 3072 (odd multi-kv-block), and the 1B GQA layout 32:8.
    for b, t in ((1, 2048), (5, 512), (1, 640), (1, 3072)):
        q = jax.random.normal(jax.random.key(0), (b, t, 32, 64), jnp.bfloat16)
        k = jax.random.normal(jax.random.key(1), (b, t, 8, 64), jnp.bfloat16)
        v = jax.random.normal(jax.random.key(2), (b, t, 8, 64), jnp.bfloat16)
        got = np.asarray(causal_flash_attention(q, k, v), np.float32)
        pos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None], (b, t))
        ref = np.asarray(causal_attention(
            q, k, v, q_positions=pos,
            kv_valid_len=jnp.full((b,), t, jnp.int32)), np.float32)
        err = np.abs(got - ref).max()
        assert err < 0.03, (b, t, err)


@check("causal flash timing vs round-3 library figure")
def t_causal_flash_timing():
    """DEVICE time from an xplane trace, not wall time.

    Wall-clock loops through the axon tunnel are unusable here: after any
    device-to-host transfer earlier in the process, per-dispatch wall time
    jumps to ~6 ms of serialized tunnel round-trips regardless of the
    kernel (round-5 finding — the r4 run of this probe "failed" at
    5.92 ms while the device time was 0.42 ms). Varied inputs defeat the
    tunnel's same-args dispatch caching; the trace gives ground truth.
    """
    import jax
    import jax.numpy as jnp

    from scripts.dev.xplane_util import traced_device_ms
    from agentic_traffic_testing_tpu.ops.pallas.chunk_flash import (
        causal_flash_attention,
    )

    t, n = 2048, 8
    args_list = [
        (jax.random.normal(jax.random.key(3 * i), (1, t, 32, 64),
                           jnp.bfloat16),
         jax.random.normal(jax.random.key(3 * i + 1), (1, t, 8, 64),
                           jnp.bfloat16),
         jax.random.normal(jax.random.key(3 * i + 2), (1, t, 8, 64),
                           jnp.bfloat16))
        for i in range(n)
    ]
    ms = traced_device_ms(jax.jit(causal_flash_attention), args_list,
                          "causal_flash", "/tmp/r4val_flash_trace")
    # Round-3 library kernel: 0.544 ms/call device at this shape (r5
    # xplane A/B); the first-party kernel measured 0.41 there. Alert if
    # it ever regresses past the library figure by 2x.
    print(f"  causal flash T=2048 1B-layout: {ms:.3f} ms/call DEVICE "
          f"(library kernel: 0.544)", flush=True)
    assert ms < 1.1, f"{ms:.3f} ms — investigate block sizes"


def run_bench(env_over: dict, tag: str, out_path: str) -> None:
    env = dict(os.environ)
    env.update({k: str(v) for k, v in env_over.items()})
    print(f"--- bench {tag}: {env_over}", flush=True)
    proc = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                          env=env, capture_output=True, text=True, cwd=REPO)
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
    if proc.returncode != 0 or not line.startswith("{"):
        print(f"  SWEEP FAIL {tag}: rc={proc.returncode} "
              f"{(proc.stderr or '').strip().splitlines()[-2:]}", flush=True)
        FAILED.append(f"sweep:{tag}")
        return
    row = json.loads(line)
    row["sweep_tag"] = tag
    with open(out_path, "a") as f:
        f.write(json.dumps(row) + "\n")
    # bench.py names the secondary series from the ACTUAL small batch
    # (default bs8_*); derive the key so a BENCH_SMALL_BATCH override in
    # env_over or the ambient env still prints the series.
    sb = int(env.get("BENCH_SMALL_BATCH", "8"))  # int-parse like bench.py
    print(f"  {tag}: {row['value']} tok/s "
          f"(bs{sb}={row.get(f'bs{sb}_toks_s')})", flush=True)


def sweep() -> None:
    out_path = os.path.join(REPO, "docs", "bench_sweep_r4.jsonl")
    # One bench invocation measures BOTH its BENCH_BATCH and bs=8, so the
    # bs=8 column comes free; bs=16 needs its own run. Small models first
    # (fail fast), 8B after. BENCH_ATTEMPTS=1: the chip is known-reachable
    # when this runs, and each extra attempt would cost engine rebuild time.
    runs = [
        ({"BENCH_MODEL": "llama-3.2-1b"}, "1b-bf16-bs32"),
        ({"BENCH_MODEL": "llama-3.2-1b", "BENCH_BATCH": 16}, "1b-bf16-bs16"),
        ({"BENCH_MODEL": "llama-3.2-1b", "BENCH_QUANTIZATION": "int8"},
         "1b-int8-bs32"),
        ({"BENCH_MODEL": "llama-3.2-1b", "BENCH_QUANTIZATION": "int8",
          "BENCH_BATCH": 16}, "1b-int8-bs16"),
        ({"BENCH_MODEL": "llama-3.2-1b", "BENCH_QUANTIZATION": "int4"},
         "1b-int4-bs32"),
        ({"BENCH_MODEL": "llama-3.2-1b", "BENCH_QUANTIZATION": "int4",
          "BENCH_BATCH": 16}, "1b-int4-bs16"),
        ({"BENCH_MODEL": "llama-3.2-1b", "BENCH_KV_CACHE_DTYPE": "fp8"},
         "1b-bf16-fp8kv-bs32"),
        ({"BENCH_MODEL": "llama-3.1-8b", "BENCH_QUANTIZATION": "int8"},
         "8b-int8-bs32"),
        ({"BENCH_MODEL": "llama-3.1-8b", "BENCH_QUANTIZATION": "int8",
          "BENCH_BATCH": 16}, "8b-int8-bs16"),
        ({"BENCH_MODEL": "llama-3.1-8b", "BENCH_QUANTIZATION": "int4"},
         "8b-int4-bs32"),
        ({"BENCH_MODEL": "llama-3.1-8b", "BENCH_QUANTIZATION": "int4",
          "BENCH_BATCH": 16}, "8b-int4-bs16"),
    ]
    for env_over, tag in runs:
        env_over.setdefault("BENCH_ATTEMPTS", 1)
        run_bench(env_over, tag, out_path)


def main() -> None:
    args = set(sys.argv[1:])
    if "--skip-r3" not in args:
        r3 = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "dev", "tpu_r3_validation.py")],
            cwd=REPO)
        if r3.returncode != 0:
            FAILED.append("r3-batch")
    for fn in (t_causal_flash, t_causal_flash_timing):
        fn()
    if "--sweep" in args:
        sweep()
    if FAILED:
        sys.exit(f"FAILED: {FAILED}")
    print("ALL TPU ROUND-4 VALIDATIONS PASS")


if __name__ == "__main__":
    main()
