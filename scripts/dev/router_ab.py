#!/usr/bin/env python3
"""Router-policy A/B on a data-parallel replica pool (fan-out workload).

The engine-level A/B for the prefix-affinity routing claim, isolated from
the HTTP layer: build an N-replica EnginePool (shared-nothing KV +
prefix-cache index per replica, one runner shared so the weights compile
once), replay the agentic fan-out shape — G scenario groups whose members
all quote the same long prompt prefix (PAPER.md workflow) — through each
routing policy, and print one JSON line per policy:

    {"policy": ..., "replicas": N, "hit_tokens": ..., "query_tokens": ...,
     "hit_rate": ..., "queue_wait_p50_s": ..., "queue_wait_p95_s": ...,
     "decode_toks_s": ..., "routed": [per-replica assignment counts]}

`prefix_affinity` should win hit_tokens (siblings land where their
scenario prefix's KV already lives) at no worse queue wait; `round_robin`
is the fairness baseline, `least_loaded` the queue-depth baseline.
Numbers feed docs/BENCHMARKS.md once measured on hardware.

Usage: python scripts/dev/router_ab.py [replicas] [groups] [fanout] [prefix_len]
Env: ROUTER_AB_MODEL (default: tiny fp32 on cpu, llama-3.2-1b bf16 on tpu),
     ROUTER_AB_POLICIES (comma list, default all three).
No reference analog (the reference runs exactly one vLLM process).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def run_policy(policy: str, *, runner, model_cfg, model: str, dtype: str,
               replicas: int, groups: int, fanout: int,
               prefix_len: int) -> dict:
    import numpy as np

    from agentic_traffic_testing_tpu.runtime.engine import (
        EngineConfig,
        LLMEngine,
    )
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams
    from agentic_traffic_testing_tpu.serving.replica_pool import EnginePool

    max_len = prefix_len + 64
    block_size = 16
    engines = [
        LLMEngine(EngineConfig(
            model=model, dtype=dtype, max_num_seqs=fanout,
            max_model_len=max_len, block_size=block_size,
            num_blocks=max(256, fanout * (-(-max_len // block_size) + 4)),
            prefix_caching=True,
        ), model_cfg=model_cfg, runner=runner)
        for _ in range(replicas)
    ]
    pool = EnginePool(engines, policy=policy)
    # Reseeded per policy: every policy must see the identical workload.
    wl = np.random.default_rng(7)
    vocab = model_cfg.vocab_size
    reqs = []
    t0 = time.monotonic()
    for _ in range(groups):
        prefix = wl.integers(10, vocab - 10, prefix_len).tolist()
        lead = pool.add_request(
            prefix + wl.integers(10, vocab - 10, 8).tolist(),
            SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True))
        while pool.has_work() and not lead.is_finished():
            pool.step()
        reqs.append(lead)
        sibs = [pool.add_request(
            prefix + wl.integers(10, vocab - 10, 8).tolist(),
            SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True))
            for _ in range(fanout - 1)]
        while pool.has_work() and not all(r.is_finished() for r in sibs):
            pool.step()
        reqs.extend(sibs)
    wall = time.monotonic() - t0
    stats = pool.kv_stats()
    waits = sorted(r.first_token_time - r.arrival_time for r in reqs
                   if r.first_token_time is not None)
    toks = sum(len(r.output_ids) for r in reqs)
    hit = int(stats.get("prefix_cache_hit_tokens", 0))
    query = int(stats.get("prefix_cache_query_tokens", 0))
    return {
        "policy": policy,
        "replicas": replicas,
        "groups": groups,
        "fanout": fanout,
        "prefix_tokens": prefix_len,
        "hit_tokens": hit,
        "query_tokens": query,
        "hit_rate": round(hit / query, 4) if query else 0.0,
        "queue_wait_p50_s": round(statistics.median(waits), 4),
        "queue_wait_p95_s": round(waits[int(0.95 * (len(waits) - 1))], 4),
        "decode_toks_s": round(toks / wall, 2),
        "routed": list(pool.routed_requests),
    }


def main(argv=None) -> list[dict]:
    argv = [int(a) for a in (argv if argv is not None else sys.argv[1:])]
    replicas = argv[0] if len(argv) > 0 else 2
    groups = argv[1] if len(argv) > 1 else 3
    fanout = argv[2] if len(argv) > 2 else 5
    prefix_len = argv[3] if len(argv) > 3 else 128

    import jax

    from agentic_traffic_testing_tpu.models.config import resolve_config
    from agentic_traffic_testing_tpu.models.llama import init_params
    from agentic_traffic_testing_tpu.runtime.runner import ModelRunner
    import jax.numpy as jnp

    platform = jax.devices()[0].platform
    model = os.environ.get(
        "ROUTER_AB_MODEL", "llama-3.2-1b" if platform == "tpu" else "tiny")
    dtype = "bfloat16" if platform == "tpu" else "float32"
    model_cfg = resolve_config(model)
    params = init_params(
        model_cfg, jax.random.key(0),
        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    runner = ModelRunner(model_cfg, params)
    print(f"devices: {jax.devices()}  replicas={replicas} groups={groups} "
          f"fanout={fanout} prefix={prefix_len} model={model}",
          file=sys.stderr, flush=True)

    policies = [p for p in os.environ.get(
        "ROUTER_AB_POLICIES",
        "round_robin,least_loaded,prefix_affinity").split(",") if p]
    # Discarded warmup pass: the runner's jit cache is shared by every
    # pool, so one small run compiles the prefill/chunk/decode shapes and
    # no measured policy pays them (the FIRST policy otherwise eats tens of
    # seconds of XLA compile inside its queue-wait numbers).
    run_policy(policies[0], runner=runner, model_cfg=model_cfg, model=model,
               dtype=dtype, replicas=replicas, groups=1, fanout=2,
               prefix_len=prefix_len)
    results = []
    for policy in policies:
        res = run_policy(policy, runner=runner, model_cfg=model_cfg,
                         model=model, dtype=dtype, replicas=replicas,
                         groups=groups, fanout=fanout, prefix_len=prefix_len)
        results.append(res)
        print(json.dumps(res), flush=True)
    return results


if __name__ == "__main__":
    main()
