#!/usr/bin/env python3
"""Speculative-decoding A/B: LLM_SPECULATION=ngram on/off, engine-isolated.

The engine-level A/B for the round-14 composable-speculation claims,
isolated from the HTTP layer: the agentic fan-out workload (short
tool-call-sized completions over highly self-repetitive, shared-prefix
sibling prompts — PAPER.md L7/L8, the regime prompt-lookup exists for)
measured with the serial fused-decode loop (`serial`) vs the fused
draft+verify dispatch (`spec`, LLM_SPECULATION=ngram — host-proposed
continuation streams, value-aligned drafts, multi-token verify through
the paged verify layout, rejected appends rolled back). One JSON line
per arm:

    {"mode": "serial"|"spec", "itl_p50_s": ..., "decode_toks_s": ...,
     "accept_rate": ..., "emitted_per_round": ..., "outputs_match": true}

The workload deliberately churns: more requests than seats (admission
mid-decode), mixed greedy/seeded sampling, mixed max_tokens, and an EOS
stop token picked from a deterministic probe pass so some lanes stop
mid-dispatch — the same churn shapes the engine suite pins token
identity under (tests/test_speculative.py). `outputs_match` asserts
every arm's completions are token-identical (the correctness half of
the claim); `accept_rate` > 0 on this workload is the win's existence
proof (the repetitive siblings make prompt-lookup drafts land). Each
arm builds its own ModelRunner over SHARED params (the spec verify
program is a different jit), so compiles are paid once per arm.
Numbers feed docs/BENCHMARKS.md once measured on hardware.

Usage: python scripts/dev/spec_ab.py [n_requests] [prompt_reps] [max_tokens]
Env: SPEC_AB_MODEL (default: tiny fp32 on cpu, llama-3.2-1b bf16 on tpu),
     SPEC_AB_SEATS (default 4 on cpu, 8 on tpu),
     SPEC_AB_TOKENS (γ drafts per round, default 3).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def agentic_prompts(n_requests: int, prompt_reps: int, vocab: int):
    """Shared-prefix fan-out siblings over a verbatim-repetitive scenario
    block — the reference's recruit→decide→execute→evaluate shape, where
    every worker re-quotes the orchestrator's period-P instruction text."""
    import numpy as np

    wl = np.random.default_rng(41)
    period = wl.integers(10, vocab - 10, 12).tolist()
    shared = period * prompt_reps                # the quoted scenario block
    return [shared + period[: 3 + (i % 5)] for i in range(n_requests)]


def run_arm(spec: int, *, params, model_cfg, model: str, dtype: str,
            seats: int, n_requests: int, prompt_reps: int, max_tokens: int,
            spec_tokens: int, decode_steps: int, reps: int) -> dict:
    from agentic_traffic_testing_tpu.runtime.engine import (
        EngineConfig,
        LLMEngine,
    )
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams
    from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

    prompts = agentic_prompts(n_requests, prompt_reps, model_cfg.vocab_size)
    block_size = 16
    max_len = max(256, max(len(p) for p in prompts) + max_tokens + 64)
    runner = ModelRunner(model_cfg, params, decode_steps=decode_steps,
                         spec_tokens=spec_tokens if spec else 0)
    eng = LLMEngine(EngineConfig(
        model=model, dtype=dtype, max_num_seqs=seats, max_model_len=max_len,
        block_size=block_size,
        num_blocks=max(256, seats * (-(-max_len // block_size) + 4)),
        speculation="ngram" if spec else None, spec_tokens=spec_tokens,
        decode_steps=decode_steps,
    ), model_cfg=model_cfg, runner=runner)

    # Deterministic probe: one greedy completion picks the EOS token the
    # churn wave will stop on — identical across arms by construction.
    probe = eng.generate(prompts[0], SamplingParams(
        temperature=0.0, max_tokens=max_tokens, ignore_eos=True))
    stop_tok = probe.output_ids[len(probe.output_ids) // 2]

    def sampling(i: int) -> SamplingParams:
        # Mixed stop lengths + mixed greedy/seeded + a reachable stop
        # token on the greedy lanes: stops land mid-round, admissions
        # follow, and the accepted-prefix commit must survive both.
        if i % 2 == 0:
            return SamplingParams(temperature=0.0,
                                  max_tokens=max_tokens - (i % 3),
                                  stop_token_ids=[stop_tok])
        return SamplingParams(temperature=0.8, top_k=20, seed=5 + i,
                              max_tokens=max_tokens // 2 + (i % 4),
                              ignore_eos=True)

    def wave():
        reqs = [eng.add_request(p, sampling(i))
                for i, p in enumerate(prompts)]
        t0 = time.monotonic()
        while eng.has_work() and not all(r.is_finished() for r in reqs):
            eng.step()
        dt = time.monotonic() - t0
        itls = [(r.finish_time - r.first_token_time)
                / max(1, len(r.output_ids) - 1)
                for r in reqs if len(r.output_ids) > 1]
        return (reqs, sum(len(r.output_ids) for r in reqs) / dt,
                statistics.median(itls))

    wave()  # warmup: pay every compile outside timing
    vals, itls = [], []
    reqs = None
    for _ in range(reps):
        reqs, toks_s, itl = wave()
        vals.append(toks_s)
        itls.append(itl)
    out = {
        "mode": "spec" if spec else "serial",
        "requests": n_requests,
        "seats": seats,
        "decode_toks_s": round(statistics.median(vals), 2),
        "itl_p50_s": round(statistics.median(itls), 5),
        "outputs": [r.output_ids for r in reqs],
    }
    if spec:
        out["accept_rate"] = round(
            eng.spec_accepted / max(1, eng.spec_drafted), 4)
        out["emitted_per_round"] = round(
            eng.spec_emitted / max(1, eng.spec_iters), 3)
    return out


def main(argv=None) -> list[dict]:
    argv = [int(a) for a in (argv if argv is not None else sys.argv[1:])]
    n_requests = argv[0] if len(argv) > 0 else 6
    prompt_reps = argv[1] if len(argv) > 1 else 6
    max_tokens = argv[2] if len(argv) > 2 else 14

    import jax
    import jax.numpy as jnp

    from agentic_traffic_testing_tpu.models.config import resolve_config
    from agentic_traffic_testing_tpu.models.llama import init_params

    platform = jax.devices()[0].platform
    model = os.environ.get(
        "SPEC_AB_MODEL", "llama-3.2-1b" if platform == "tpu" else "tiny")
    # fp32 off-TPU so the identity gate is exact at this script's short
    # completion horizon (ops/speculative.py documents the step-shape
    # byte drift that can flip a near-tie at much longer lengths).
    dtype = "bfloat16" if platform == "tpu" else "float32"
    seats = int(os.environ.get(
        "SPEC_AB_SEATS", "8" if platform == "tpu" else "4"))
    spec_tokens = int(os.environ.get("SPEC_AB_TOKENS", "3"))
    decode_steps = 2 if platform != "tpu" else 8
    reps = 3 if platform == "tpu" else 1
    model_cfg = resolve_config(model)
    params = init_params(
        model_cfg, jax.random.key(0),
        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    print(f"devices: {jax.devices()}  requests={n_requests} seats={seats} "
          f"model={model}", file=sys.stderr, flush=True)

    common = dict(params=params, model_cfg=model_cfg, model=model,
                  dtype=dtype, seats=seats, n_requests=n_requests,
                  prompt_reps=prompt_reps, max_tokens=max_tokens,
                  spec_tokens=spec_tokens, decode_steps=decode_steps,
                  reps=reps)
    results = [run_arm(sp, **common) for sp in (0, 1)]
    # Correctness gate: both arms must produce identical completions
    # (exact off-TPU in fp32; on TPU bf16 near-ties may flip — the
    # documented step-shape caveat — so the gate loosens to agreement).
    if platform == "tpu":
        flat = [[t for o in r["outputs"] for t in o] for r in results]
        agree = (sum(a == b for a, b in zip(*flat)) / max(1, len(flat[0])))
        match = (results[0]["outputs"][0][:1] == results[1]["outputs"][0][:1]
                 and agree >= 0.9)
    else:
        match = results[0]["outputs"] == results[1]["outputs"]
    for r in results:
        r["outputs_match"] = bool(match)
        r.pop("outputs")
        print(json.dumps(r), flush=True)
    return results


if __name__ == "__main__":
    main()
