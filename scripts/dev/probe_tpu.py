#!/usr/bin/env python3
"""Probe the axon TPU tunnel: exit 0 iff a device is reachable and computes.

Used by the round-4 recovery watcher (and by hand). When the tunnel is
wedged, backend init hangs ~25 min before raising UNAVAILABLE — run under
a timeout.
"""

import time

import jax

t0 = time.time()
devices = jax.devices()
print(f"TUNNEL UP: {devices} in {time.time() - t0:.1f}s", flush=True)
import jax.numpy as jnp

x = jnp.ones((128, 128), jnp.bfloat16)
print("compute:", float((x @ x).sum()))
