#!/usr/bin/env python3
"""Open-loop λ-sweep soak driver for the round-15 agentic traffic plane.

The SAME synthesized AgentVerse DAG trace replays open-loop at each
offered rate, twice per rate — `clean` (no faults, unbounded queue) and
`chaos` (a seeded dispatch-fault spec + a bounded wait queue, the
chaos_ab.py pattern) — against a fresh in-process engine with the
step-clock telemetry plane on. One JSON line per run:

    {"mode": "clean"|"chaos", "rate": λ, "completed": N, "shed": N, ...,
     "all_terminated": true, "counters_reconcile": true}

Gates (the ISSUE-15 acceptance criteria, machine-checked here and in
tests/test_scripts.py::test_loadgen_soak_smoke):

  * all_terminated       — every fired request reached a terminal state
                           (ok, shed, deadline, or structured error).
  * counters_reconcile   — the loadgen report's TTFT-SLO met/violated and
                           shed counts EQUAL the engine's Prometheus
                           counters (llm_slo_attainment_total drained from
                           the step clock; num_shed, the value behind the
                           SHED terminals llm_requests_shed_total counts).
  * attainment_delta     — per rate, clean attainment >= chaos attainment
                           (fault injection cannot improve SLO attainment).

A final `sweep` line reports the clean arms' capacity knee (max λ at
>= the attainment target) and serves the loadgen's own Prometheus
registry once on an ephemeral port to prove the second exposition
surface scrapes with every family present.

When invoked as a script the sweep line also lands on disk as
`BENCH_LOADGEN_rNN.json` at the repo root (next free round index, the
BENCH_r* naming) so successive soaks accumulate a λ-knee-over-rounds
trajectory next to the throughput series; in-process callers (tests)
opt in with SOAK_WRITE_BENCH=1.

Usage: python scripts/dev/loadgen_soak.py [tasks] [max_tokens]
Env: SOAK_MODEL (default tiny/fp32 on cpu, llama-3.2-1b/bf16 on tpu),
     SOAK_RATES (comma λ list, default "4,8"),
     SOAK_FAULT_SPEC (default "dispatch_error:p=0.1"),
     SOAK_ATTAINMENT_TARGET (default 0.5 on cpu — the tiny-engine knee),
     SOAK_WRITE_BENCH / SOAK_BENCH_DIR (trajectory file, see above).
"""

from __future__ import annotations

import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def write_bench_trajectory(summary: dict) -> str:
    """Persist one sweep summary as the next `BENCH_LOADGEN_rNN.json`
    round at the repo root (or SOAK_BENCH_DIR): the λ-knee trajectory
    the ISSUE-16 acceptance reads. Rounds are append-only — an existing
    rNN is never rewritten, so the series stays a history."""
    root = os.environ.get("SOAK_BENCH_DIR") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "..")
    n = 1
    while os.path.exists(
            os.path.join(root, f"BENCH_LOADGEN_r{n:02d}.json")):
        n += 1
    path = os.path.join(root, f"BENCH_LOADGEN_r{n:02d}.json")
    with open(path, "w") as f:
        json.dump({"n": n, **summary}, f, indent=2)
        f.write("\n")
    return os.path.abspath(path)


def run_one(*, chaos: bool, rate: float, trace, runner, model_cfg,
            model: str, dtype: str, seats: int, fault_spec: str) -> dict:
    from agentic_traffic_testing_tpu.loadgen.replay import (
        engine_geometry,
        replay_against_engine,
    )
    from agentic_traffic_testing_tpu.runtime.engine import (
        EngineConfig,
        LLMEngine,
    )
    from agentic_traffic_testing_tpu.serving.metrics import LLMMetrics

    max_len, num_blocks = engine_geometry(trace, seats)
    eng = LLMEngine(EngineConfig(
        model=model, dtype=dtype, max_num_seqs=seats, max_model_len=max_len,
        block_size=16, num_blocks=num_blocks,
        step_trace=1,
        fault_spec=fault_spec if chaos else "",
        fault_seed=23,
        # Chaos arm: bounded queue so open-loop overload SHEDS (the
        # engine-side backstop terminal) instead of queueing unboundedly.
        max_queue=2 * seats if chaos else 0,
    ), model_cfg=model_cfg, runner=runner)
    records, report = replay_against_engine(
        eng, trace, arrival="poisson", rate=rate, seed=11,
        vocab_size=model_cfg.vocab_size)

    # Reconcile against the engine's Prometheus counters: drain the step
    # clock into a real LLMMetrics registry and read the families back.
    m = LLMMetrics()
    m.observe_step_clock([eng.telemetry])
    get = m.registry.get_sample_value
    prom_met = get("llm_slo_attainment_total",
                   {"slo": "ttft", "status": "met"}) or 0
    prom_violated = get("llm_slo_attainment_total",
                        {"slo": "ttft", "status": "violated"}) or 0
    rep_met = sum(c["ttft_met"] for c in report["slo"].values())
    rep_total = sum(c["ttft_total"] for c in report["slo"].values())
    reconcile = (int(prom_met) == rep_met
                 and int(prom_met + prom_violated) == rep_total
                 and eng.num_shed == report["shed"])
    return {
        "mode": "chaos" if chaos else "clean",
        "rate": rate,
        "requests": report["requests"],
        "completed": report["completed"],
        "shed": report["shed"],
        "deadline": report["deadline"],
        "errors": report["errors"],
        "dispatch_failures": eng.num_dispatch_failures,
        "ttft_attainment": report["ttft_attainment"],
        "achieved_rate": report["achieved_rate"],
        "goodput_rate": report["goodput_rate"],
        "schedule_lag_p99_s": report["schedule_lag_p99_s"],
        "all_terminated": report["all_terminated"],
        "engine_slo_met": int(prom_met),
        "engine_slo_violated": int(prom_violated),
        "engine_shed": eng.num_shed,
        "counters_reconcile": reconcile,
    }


def scrape_loadgen_surface(trace) -> dict:
    """Prove the loadgen's own exposition surface: serve the registry on
    an ephemeral port, scrape it over HTTP, and check the
    always-registered families are present BEFORE any request fired."""
    from agentic_traffic_testing_tpu.loadgen.measure import (
        LoadgenMetrics,
        MetricsExposition,
    )

    metrics = LoadgenMetrics.for_trace(trace)
    exposition = MetricsExposition(metrics, port=0, host="127.0.0.1")
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{exposition.port}/metrics",
                timeout=10) as resp:
            payload = resp.read().decode()
    finally:
        exposition.close()
    families = ("loadgen_offered_requests_total", "loadgen_requests_total",
                "loadgen_ttft_seconds", "loadgen_itl_seconds",
                "loadgen_e2e_seconds", "loadgen_schedule_lag_seconds",
                "loadgen_slo_attainment_total", "loadgen_offered_rate",
                "loadgen_achieved_rate", "loadgen_goodput_rate")
    return {"port_scraped": True,
            "families_present": all(f in payload for f in families)}


def main(argv=None) -> list:
    argv = [int(a) for a in (argv if argv is not None else sys.argv[1:])]
    tasks = argv[0] if len(argv) > 0 else 2
    max_tokens = argv[1] if len(argv) > 1 else 8

    import jax
    import jax.numpy as jnp

    from agentic_traffic_testing_tpu.loadgen.measure import capacity_knee
    from agentic_traffic_testing_tpu.loadgen.trace import (
        synthesize_agentverse_trace,
    )
    from agentic_traffic_testing_tpu.models.config import resolve_config
    from agentic_traffic_testing_tpu.models.llama import init_params
    from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

    platform = jax.devices()[0].platform
    model = os.environ.get(
        "SOAK_MODEL", "llama-3.2-1b" if platform == "tpu" else "tiny")
    dtype = "bfloat16" if platform == "tpu" else "float32"
    seats = 16 if platform == "tpu" else 4
    rates = [float(r) for r in
             os.environ.get("SOAK_RATES", "4,8").split(",") if r]
    fault_spec = os.environ.get("SOAK_FAULT_SPEC", "dispatch_error:p=0.1")
    target = float(os.environ.get(
        "SOAK_ATTAINMENT_TARGET", "0.99" if platform == "tpu" else "0.5"))

    model_cfg = resolve_config(model)
    params = init_params(
        model_cfg, jax.random.key(0),
        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    runner = ModelRunner(model_cfg, params,
                         decode_steps=16 if platform == "tpu" else 1)
    trace = synthesize_agentverse_trace(tasks=tasks, seed=5,
                                        max_tokens=max_tokens)
    print(f"devices: {jax.devices()}  trace={trace.name} "
          f"nodes={len(trace.nodes)} rates={rates} spec={fault_spec!r}",
          file=sys.stderr, flush=True)

    common = dict(trace=trace, runner=runner, model_cfg=model_cfg,
                  model=model, dtype=dtype, seats=seats,
                  fault_spec=fault_spec)
    # Discarded warmup pass: the shared runner compiles every
    # prefill/decode shape the trace exercises OUTSIDE the measured
    # arms, so the first measured run's TTFTs are not compile stalls.
    run_one(chaos=False, rate=rates[0], **common)
    print("warmup replay done", file=sys.stderr, flush=True)
    results = []
    sweep = []
    for rate in rates:
        clean = run_one(chaos=False, rate=rate, **common)
        chaos = run_one(chaos=True, rate=rate, **common)
        # Attainment-delta gate, goodput-guarded: fault injection must
        # not produce MORE SLO-met completions per second than the
        # clean arm (it destroys work). Raw attainment alone can move
        # either way under chaos — errored requests attain no verdict,
        # so killing work shortens the survivors' queues (survivor
        # bias) — which is why a negative delta is tolerated exactly
        # when the chaos arm actually errored work away.
        delta = ((clean["ttft_attainment"] or 0.0)
                 - (chaos["ttft_attainment"] or 0.0))
        goodput_ok = (chaos["goodput_rate"]
                      <= clean["goodput_rate"] * 1.1 + 0.5)
        for r in (clean, chaos):
            r["attainment_delta"] = round(delta, 4)
            r["attainment_delta_ok"] = goodput_ok and (
                delta >= -0.101 or chaos["errors"] > 0)
            print(json.dumps(r), flush=True)
            results.append(r)
        sweep.append((rate, {"ttft_attainment": clean["ttft_attainment"]}))
    summary = {
        "mode": "sweep",
        "trace": trace.name,
        "model": model,
        "rates": rates,
        "attainment_target": target,
        "ttft_attainment_by_rate": {
            f"{rate:g}": rep["ttft_attainment"] for rate, rep in sweep},
        "max_sustainable_lambda": capacity_knee(sweep, target=target),
        **scrape_loadgen_surface(trace),
    }
    print(json.dumps(summary), flush=True)
    results.append(summary)
    if os.environ.get("SOAK_WRITE_BENCH", "0") not in ("0", "false"):
        print(f"trajectory -> {write_bench_trajectory(summary)}",
              file=sys.stderr, flush=True)
    return results


if __name__ == "__main__":
    os.environ.setdefault("SOAK_WRITE_BENCH", "1")
    main()
