"""Shared xplane-trace device-timing harness for the dev perf scripts.

Wall-clock loops through the axon tunnel are unusable for kernel timing:
after any device-to-host transfer, per-dispatch wall time jumps to ~6 ms
of serialized round trips regardless of the kernel, and the tunnel caches
same-args dispatches into impossibly-fast readings (round-5 finding: the
r4 probe read 5.92 ms wall for a 0.41 ms kernel). Device-plane op time
from a `jax.profiler.trace` over VARIED inputs is the ground truth; this
module is the one place that runs that measurement and parses the trace,
so the validation probe and the A/B script cannot drift apart.
`profile_decode.summarize()` keeps its richer per-op/idle-gap report.
"""

from __future__ import annotations

import glob
import os
import shutil


def device_op_time_ps(trace_dir: str, match: str) -> int:
    """Sum device-plane exclusive-line event time (ps) for ops whose HLO
    name contains `match`. Raises RuntimeError (NOT SystemExit — the
    validation batch's @check wrapper must be able to record the failure
    and keep going) if no trace was written."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = sorted(glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                             recursive=True))
    if not paths:
        raise RuntimeError(f"no .xplane.pb under {trace_dir} — profiler "
                           f"wrote no trace (plugin missing? dir unwritable?)")
    xs = xplane_pb2.XSpace()
    with open(paths[-1], "rb") as f:
        xs.ParseFromString(f.read())
    tot_ps = 0
    for plane in xs.planes:
        if "TPU" not in plane.name:
            continue
        names = dict(plane.event_metadata.items())
        for line in plane.lines:
            lname = line.name.lower()
            # 'Async XLA Ops' spans overlap compute and a module-level
            # line wraps its ops — either would double-count.
            if "module" in lname or "async" in lname:
                continue
            for ev in line.events:
                md = names.get(ev.metadata_id)
                if md and match in md.name:
                    tot_ps += ev.duration_ps
    return tot_ps


def traced_device_ms(fn, args_list, match: str, trace_dir: str) -> float:
    """DEVICE ms/call for `fn` over `args_list` (one call per arg tuple —
    vary the inputs or the tunnel's same-args caching deflates the
    number). Compiles outside the trace, clears any stale trace dir, and
    raises RuntimeError if no device event matched (HLO naming changed?)
    so every caller fails loudly the same way."""
    fn(*args_list[0]).block_until_ready()            # compile
    import jax

    shutil.rmtree(trace_dir, ignore_errors=True)
    with jax.profiler.trace(trace_dir):
        outs = [fn(*a) for a in args_list]
        for o in outs:
            o.block_until_ready()
    ms = device_op_time_ps(trace_dir, match) / 1e9 / len(args_list)
    if ms == 0.0:
        raise RuntimeError(f"no device events matching {match!r} in the "
                           f"trace under {trace_dir} — filter broken?")
    return ms
