#!/usr/bin/env python3
"""Micro-bench the paged decode attention kernel at bench.py's live shapes.

The round-5 bs=32 trace (scripts/dev/profile_decode.py) shows
paged_attention_decode_dma2 at ~76 us/call while the KV bytes actually
resident for the mean ~150-token contexts stream in ~28 us at HBM rate —
the kernel is the single largest off-roofline item in the decode step.
Two over-read sources are visible in the kernel source:

  * tail-chunk ceiling: the chunk loop copies `pages_per_chunk` full pages
    per chunk even when the last chunk holds fewer real pages (clamped
    index re-copies page w-1), a ~60% byte over-read at 10 pages/seq;
  * lane padding: the pool pads head_dim 64 -> 128, doubling every byte.

This harness times the kernel in isolation (xplane device-plane, same
methodology as flash_ab.py) at the bench workload's shapes so fixes can be
A/B'd without a full bench run.

Usage: python scripts/dev/paged_decode_ab.py [ctx] [batch] [pages_per_chunk]
                                             [block_size] [hd_pool]
Env: PAGED_AB_KERNEL=dma2|dma3 (default dma2).
No reference analog (the reference delegates paging to vLLM).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
import jax.numpy as jnp

from scripts.dev.quant_ab import device_total_ms

N = 8


def main() -> None:
    argv = [int(a) for a in sys.argv[1:]]
    ctx = argv[0] if len(argv) > 0 else 150
    b = argv[1] if len(argv) > 1 else 32
    cp = argv[2] if len(argv) > 2 else 8

    from agentic_traffic_testing_tpu.ops.pallas import paged_attention as pa

    kname = os.environ.get("PAGED_AB_KERNEL", "dma2")
    kernel = {"dma2": pa.paged_attention_decode_dma2,
              "dma3": pa.paged_attention_decode_dma3}[kname]

    # bench.py 1B layout: 16 layers, 8 kv heads, 512 blocks of 16, hd
    # lane-padded to 128 (real head_dim 64). Block size and pool hd are
    # overridable to A/B page granularity and padding (pool token capacity
    # is held constant at 8192).
    L, KH, BS, HD = 16, 8, 16, 128
    BS = argv[3] if len(argv) > 3 else BS
    HD = argv[4] if len(argv) > 4 else HD
    NB = 8192 // BS
    H = 32
    hd_real = 64
    print(f"devices: {jax.devices()}  ctx={ctx} B={b} cp={cp} "
          f"pool=[{L},{KH},{NB},{BS},{HD}]", flush=True)

    max_blocks = NB // max(b, 1)
    n_pages = (ctx + BS - 1) // BS
    assert n_pages <= max_blocks

    key = jax.random.key(0)
    kp = jax.random.normal(key, (L, KH, NB, BS, HD), jnp.bfloat16)
    vp = jax.random.normal(key, (L, KH, NB, BS, HD), jnp.bfloat16)
    bt = jnp.arange(b * max_blocks, dtype=jnp.int32).reshape(b, max_blocks) % NB
    cl = jnp.full((b,), ctx, jnp.int32)
    qs = [jax.random.normal(jax.random.key(i), (b, H, HD), jnp.bfloat16)
          for i in range(N)]

    lay = jnp.int32(3)

    def fn(q):
        return kernel(q, kp, vp, bt, cl, layer=lay, pages_per_chunk=cp)

    ms = device_total_ms(fn, [(q,) for q in qs], "/tmp/paged_decode_ab")
    # real KV bytes at this context (unpadded head dim), vs copied bytes
    # (tail-guarded: only n_pages pages per sequence are DMA'd)
    real = b * ctx * KH * hd_real * 2 * 2
    copied = b * n_pages * BS * KH * HD * 2 * 2
    print(f"  {kname} cp={cp} bs={BS} hd={HD}: {ms * 1e3:8.1f} us/call DEVICE  "
          f"(copied {copied / 1e6:.1f} MB -> {copied / (ms / 1e3) / 1e9:5.0f} "
          f"GB/s; real KV {real / 1e6:.1f} MB)", flush=True)


if __name__ == "__main__":
    main()
