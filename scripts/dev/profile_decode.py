#!/usr/bin/env python3
"""Trace the engine's decode workload and print a device-op time summary.

Runs the bench.py throughput workload (1B bf16, bs=8 by default) under
`jax.profiler.trace`, then parses the written xplane protobuf and prints
per-op total durations for the busiest device plane — the tool behind the
decode-step anatomy in docs/BENCHMARKS.md. No reference analog (the
reference profiles via nsight outside the repo).

Usage: python scripts/dev/profile_decode.py [trace_dir]
Env: same BENCH_* knobs as bench.py; PROFILE_TOP (default 40).
"""

from __future__ import annotations

import glob
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

# Honor an explicit JAX_PLATFORMS=cpu despite the axon sitecustomize
# (wedged-tunnel hang trap - see agentic_traffic_testing_tpu/platform_guard.py).
from agentic_traffic_testing_tpu.platform_guard import force_cpu_if_requested  # noqa: E402

force_cpu_if_requested()


def run_workload(trace_dir: str) -> None:
    import jax
    import numpy as np

    from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams

    platform = jax.devices()[0].platform
    model = os.environ.get("BENCH_MODEL",
                           "llama-3.2-1b" if platform == "tpu" else "debug-512")
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    total = int(os.environ.get("BENCH_TOTAL_REQUESTS", str(3 * batch)))
    prompt_len = int(os.environ.get("BENCH_PROMPT_LEN", "128"))
    decode_tokens = int(os.environ.get("BENCH_DECODE_TOKENS", "64"))
    ds = os.environ.get("BENCH_DECODE_STEPS")
    decode_steps = int(ds) if ds else (32 if platform == "tpu" else None)

    cfg = EngineConfig(model=model, max_num_seqs=batch,
                       max_model_len=max(512, prompt_len + decode_tokens + 8),
                       decode_steps=decode_steps,
                       quantization=os.environ.get("BENCH_QUANTIZATION") or None)
    eng = LLMEngine(cfg)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, eng.model_cfg.vocab_size, prompt_len).tolist()
               for _ in range(total)]
    sp = SamplingParams(max_tokens=decode_tokens, ignore_eos=True)

    # Warm (compile) pass outside the trace so the trace holds steady state.
    for p in prompts[:batch]:
        eng.add_request(p, sp)
    while eng.has_work():
        eng.step()

    with jax.profiler.trace(trace_dir):
        for p in prompts:
            eng.add_request(p, sp)
        while eng.has_work():
            eng.step()


def summarize(trace_dir: str, top: int) -> None:
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = sorted(glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                             recursive=True))
    if not paths:
        raise SystemExit(f"no .xplane.pb under {trace_dir}")
    xspace = xplane_pb2.XSpace()
    with open(paths[-1], "rb") as f:
        xspace.ParseFromString(f.read())

    best = None  # busiest non-host plane = the device compute timeline
    for plane in xspace.planes:
        total_ps = sum(ev.duration_ps for line in plane.lines
                       for ev in line.events)
        lname = plane.name.lower()
        if "host" in lname or "cpu" in lname or "python" in lname:
            continue
        if best is None or total_ps > best[0]:
            best = (total_ps, plane)
    if best is None:
        raise SystemExit("no device plane found")
    _, plane = best
    names = dict(plane.event_metadata.items())

    # Per-op totals from EXCLUSIVE-time lines only. 'Async XLA Ops' events
    # span their whole issue→done DMA window (they overlap compute), and a
    # module-level line wraps its ops — summing either alongside 'XLA Ops'
    # double-counts and makes overlapped prefetches look like hot ops.
    by_op: dict[str, list[float]] = defaultdict(lambda: [0.0, 0])
    line_total_ps = 0.0
    for line in plane.lines:
        lname = line.name.lower()
        if "module" in lname or "async" in lname:
            continue
        for ev in line.events:
            md = names.get(ev.metadata_id)
            name = md.name if md else str(ev.metadata_id)
            acc = by_op[name]
            acc[0] += ev.duration_ps
            acc[1] += 1
            line_total_ps += ev.duration_ps
    print(f"plane: {plane.name}  total device-op time (exclusive lines): "
          f"{line_total_ps / 1e9:.3f} ms")
    rows = sorted(by_op.items(), key=lambda kv: -kv[1][0])[:top]
    for name, (ps, n) in rows:
        print(f"{ps / 1e9:10.3f} ms  x{n:<6d} {name[:110]}")

    # Idle-gap analysis at OP granularity: where the chip sat waiting.
    # Prefer the op-level line by name — a module/step-level line's events
    # wrap their ops plus any intra-module idle, so picking the line with
    # the largest duration sum would make the gap analysis tautologically
    # ~100% busy whenever op-level idle exists.
    op_lines = [l for l in plane.lines if "op" in l.name.lower()]
    pool = op_lines or list(plane.lines)
    if not pool:
        return
    busiest = max(pool, key=lambda l: sum(e.duration_ps for e in l.events))
    evs = sorted(busiest.events, key=lambda e: e.offset_ps)
    if not evs:
        return
    span_ps = (evs[-1].offset_ps + evs[-1].duration_ps) - evs[0].offset_ps
    busy_ps, cur_end = 0, evs[0].offset_ps
    gaps: list[tuple[int, str, str]] = []
    prev_name = ""
    for ev in evs:
        start, end = ev.offset_ps, ev.offset_ps + ev.duration_ps
        md = names.get(ev.metadata_id)
        name = (md.name if md else str(ev.metadata_id))[:60]
        if start > cur_end:
            gaps.append((start - cur_end, prev_name, name))
        busy_ps += max(0, end - max(start, cur_end))
        if end > cur_end:
            cur_end = end
            prev_name = name
    print(f"\nline '{busiest.name}': span {span_ps/1e9:.1f} ms, busy "
          f"{busy_ps/1e9:.1f} ms ({100*busy_ps/max(1,span_ps):.1f}%), "
          f"{len(gaps)} gaps totalling {(span_ps-busy_ps)/1e9:.1f} ms")
    for g, before, after in sorted(gaps, reverse=True)[:15]:
        print(f"  gap {g/1e9:8.3f} ms  after [{before}]  before [{after}]")


def main() -> None:
    trace_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/decode_trace"
    top = int(os.environ.get("PROFILE_TOP", "40"))
    run_workload(trace_dir)
    summarize(trace_dir, top)


if __name__ == "__main__":
    main()
