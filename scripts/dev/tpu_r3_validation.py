#!/usr/bin/env python3
"""One-chip validation batch for the round-3 TPU-pending paths.

Everything round 3 added that interpret-mode cannot fully vouch for:
  1. flash prefill with the pow2-divisor BlockSizes (incl. odd buckets),
  2. fp8 KV pages through the dma2 kernel (Mosaic 8-bit tiling),
  3. int4 K-group scales through the kernel's sub-dot path,
  4. the default bench configuration end to end.

Run whenever a real chip is reachable: python scripts/dev/tpu_r3_validation.py
Prints PASS/FAIL per item; exits non-zero on any failure.
"""

from __future__ import annotations

import os
import sys
import traceback

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

FAILED = []


def check(name):
    def deco(fn):
        def run():
            try:
                fn()
                print(f"PASS {name}")
            except Exception:
                FAILED.append(name)
                print(f"FAIL {name}")
                traceback.print_exc()
        return run
    return deco


@check("flash prefill blocks (512/2048/3072-odd buckets)")
def t_flash():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from agentic_traffic_testing_tpu.ops.flash_prefill import prefill_attention
    from agentic_traffic_testing_tpu.ops.jnp_ops import causal_attention

    for T in (512, 2048, 3072):
        q = jax.random.normal(jax.random.key(0), (1, T, 32, 64), jnp.bfloat16)
        k = jax.random.normal(jax.random.key(1), (1, T, 8, 64), jnp.bfloat16)
        v = jax.random.normal(jax.random.key(2), (1, T, 8, 64), jnp.bfloat16)
        pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (1, T))
        sl = jnp.asarray([T - 64], jnp.int32)
        a = np.asarray(prefill_attention(q, k, v, q_positions=pos,
                                         kv_valid_len=sl), np.float32)
        b = np.asarray(causal_attention(q, k, v, q_positions=pos,
                                        kv_valid_len=sl), np.float32)
        real = T - 64
        err = np.abs(a[:, :real] - b[:, :real]).max()
        assert err < 0.03, (T, err)


@check("fp8 KV pages through dma2 on hardware")
def t_fp8():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from agentic_traffic_testing_tpu.ops.pallas.paged_attention import (
        paged_attention_decode_dma2,
    )
    from agentic_traffic_testing_tpu.runtime import kv_cache as kvc
    from agentic_traffic_testing_tpu.ops.attention_backend import (
        paged_decode_attention,
    )

    L, KH, NB, BS, hd = 2, 8, 16, 16, 128
    shape = (L, KH, NB, BS, hd)
    k_pages = jax.random.normal(jax.random.key(3), shape,
                                jnp.float32).astype(jnp.float8_e4m3fn)
    v_pages = jax.random.normal(jax.random.key(4), shape,
                                jnp.float32).astype(jnp.float8_e4m3fn)
    q = jax.random.normal(jax.random.key(5), (2, 32, hd), jnp.bfloat16)
    bt = jnp.asarray([[1, 2, 0, 0], [3, 4, 0, 0]], jnp.int32)
    ctx = jnp.asarray([20, 27], jnp.int32)
    got = np.asarray(paged_attention_decode_dma2(
        q, k_pages, v_pages, bt, ctx, layer=1), np.float32)
    ref = np.asarray(paged_decode_attention(
        q[:, None], k_pages, v_pages, bt, ctx - 1, mode="gather",
        layer=1)[:, 0], np.float32)
    assert np.abs(got - ref).max() < 0.03, np.abs(got - ref).max()


@check("int4 K-group kernel on hardware")
def t_int4g():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from agentic_traffic_testing_tpu.models.quant import _unpack4, quantize_array4
    from agentic_traffic_testing_tpu.ops.pallas.int4_matmul import int4_matmul

    x = jax.random.normal(jax.random.key(6), (8, 4096), jnp.bfloat16)
    w = jax.random.normal(jax.random.key(7), (4096, 1024), jnp.float32)
    qg = quantize_array4(w, k_group=512)
    ref = np.asarray(x.astype(jnp.float32)
                     @ _unpack4(qg.packed, qg.scale, jnp.float32), np.float32)
    got = np.asarray(int4_matmul(x, qg.packed, qg.scale, n_block=1024,
                                 out_dtype=jnp.float32), np.float32)
    rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-6)
    assert rel < 0.02, rel  # bf16 activation rounding only


@check("fp8 engine decode throughput sanity (1B)")
def t_fp8_engine():
    import numpy as np

    from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams

    eng = LLMEngine(EngineConfig(model="llama-3.2-1b", dtype="bfloat16",
                                 max_num_seqs=8, max_model_len=512,
                                 kv_cache_dtype="fp8", decode_steps=32))
    rng = np.random.default_rng(0)
    reqs = [eng.add_request(rng.integers(10, 1000, 128).tolist(),
                            SamplingParams(temperature=0.0, max_tokens=32,
                                           ignore_eos=True))
            for _ in range(8)]
    while eng.has_work() and not all(r.is_finished() for r in reqs):
        eng.step()
    assert all(len(r.output_ids) == 32 for r in reqs)


@check("chunk-flash kernel on hardware")
def t_chunk_flash():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from agentic_traffic_testing_tpu.ops.jnp_ops import causal_attention
    from agentic_traffic_testing_tpu.ops.pallas.chunk_flash import (
        chunk_flash_attention,
    )

    B, C, H, KH, hd = 1, 2048, 32, 8, 64
    W_TOK, start = 4096, 4000
    q = jax.random.normal(jax.random.key(8), (B, C, H, hd), jnp.bfloat16)
    kk = jax.random.normal(jax.random.key(9), (B, W_TOK + C, KH, hd), jnp.bfloat16)
    vv = jax.random.normal(jax.random.key(10), (B, W_TOK + C, KH, hd), jnp.bfloat16)
    got = np.asarray(chunk_flash_attention(
        q, kk, vv, jnp.int32(start), prior_len=W_TOK), np.float32)
    positions = start + jnp.arange(C, dtype=jnp.int32)[None]
    kv_pos = jnp.concatenate(
        [jnp.arange(W_TOK, dtype=jnp.int32)[None], positions], axis=1)
    kv_mask = jnp.concatenate(
        [jnp.arange(W_TOK, dtype=jnp.int32)[None] < start,
         jnp.ones((1, C), bool)], axis=1)
    ref = np.asarray(causal_attention(
        q, kk, vv, q_positions=positions, kv_positions=kv_pos,
        kv_valid_mask=kv_mask), np.float32)
    assert np.abs(got - ref).max() < 0.03


def main() -> None:
    for fn in (t_flash, t_fp8, t_int4g, t_fp8_engine, t_chunk_flash):
        fn()
    if FAILED:
        sys.exit(f"FAILED: {FAILED}")
    print("ALL TPU ROUND-3 VALIDATIONS PASS")


if __name__ == "__main__":
    main()
