#!/usr/bin/env python3
"""A/B the round-10 KV-quantization stack: bf16 vs fp8 vs int8 pages.

One row per KV dtype on the SAME weights and the SAME greedy workload:

    decode_toks_s       engine decode throughput (wall, request wave)
    kv_bytes_per_step   analytic streamed KV bytes per fused decode step
                        (pages + the int8 per-page scale stream)
    logit_rms           relative RMS of the first decode step's logits vs
                        the bf16-KV oracle (model-level, one prompt)
    first_token_match   first greedy token equals the bf16 engine's
    token_identity      greedy agreement fraction over the whole workload
    fused_outputs_match the LLM_FUSED_KV_WRITE=1 engine of the same dtype
                        reproduces the separate-dispatch outputs exactly

On CPU (the test smoke) the numbers are semantics checks; on hardware the
rows size the streamed-byte reduction against the bs32 roofline_frac
target (ROADMAP standing ask — run together with bench.py's decode_anatomy
probe).

Usage: python scripts/dev/kv_quant_ab.py [n_requests] [prompt_len] [decode_tokens]
Env:   KV_QUANT_AB_MODEL (default llama-3.2-1b on TPU / tiny elsewhere)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from agentic_traffic_testing_tpu.platform_guard import force_cpu_if_requested

force_cpu_if_requested()


def main(argv: list[str] | None = None) -> list[dict]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from agentic_traffic_testing_tpu.models.config import resolve_config
    from agentic_traffic_testing_tpu.models.llama import (
        decode_step,
        init_params,
        prefill,
    )
    from agentic_traffic_testing_tpu.runtime.engine import (
        EngineConfig,
        LLMEngine,
    )
    from agentic_traffic_testing_tpu.runtime.kv_cache import (
        TRASH_BLOCK,
        make_kv_cache,
    )
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams
    from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

    argv = sys.argv[1:] if argv is None else argv
    n_requests = int(argv[0]) if len(argv) > 0 else 4
    prompt_len = int(argv[1]) if len(argv) > 1 else 48
    decode_tokens = int(argv[2]) if len(argv) > 2 else 12

    platform = jax.devices()[0].platform
    model = os.environ.get(
        "KV_QUANT_AB_MODEL", "llama-3.2-1b" if platform == "tpu" else "tiny")
    mcfg = resolve_config(model)
    dtype = jnp.bfloat16 if platform == "tpu" else jnp.float32
    dtype_name = "bfloat16" if platform == "tpu" else "float32"
    params = init_params(mcfg, jax.random.key(0), dtype=dtype)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(10, mcfg.vocab_size - 10, prompt_len).tolist()
               for _ in range(n_requests)]
    block_size = 16
    max_len = prompt_len + decode_tokens + 16
    num_blocks = n_requests * (-(-max_len // block_size) + 4) + 1

    def build(kv, fused):
        runner = ModelRunner(mcfg, params, decode_steps=1,
                             fused_kv_write=fused)
        return LLMEngine(EngineConfig(
            model=model, dtype=dtype_name, max_num_seqs=n_requests,
            max_model_len=max_len, block_size=block_size,
            num_blocks=num_blocks, kv_cache_dtype=kv,
            fused_kv_write=int(fused),
        ), model_cfg=mcfg, params=params, runner=runner)

    def drive(eng):
        reqs = [eng.add_request(p, SamplingParams(
            temperature=0.0, max_tokens=decode_tokens, ignore_eos=True))
            for p in prompts]
        t0 = time.monotonic()
        while eng.has_work() and not all(r.is_finished() for r in reqs):
            eng.step()
        dt = time.monotonic() - t0
        return [r.output_ids for r in reqs], dt

    def first_step_logits(kv):
        tt = -(-prompt_len // block_size) * block_size
        toks = np.zeros((1, tt), np.int32)
        toks[0, :prompt_len] = prompts[0]
        nb = tt // block_size + 3
        bt = np.full((1, nb), TRASH_BLOCK, np.int32)
        bt[0, : nb - 1] = np.arange(1, nb)
        quant = kv == "int8"
        dt_ = (jnp.float8_e4m3fn if kv == "fp8"
               else jnp.int8 if quant else dtype)
        cache = make_kv_cache(mcfg, nb, block_size, dt_, quantized=quant)
        logits, cache = prefill(params, mcfg, jnp.asarray(toks), cache,
                                jnp.asarray(bt),
                                jnp.asarray([prompt_len], jnp.int32))
        nxt = jnp.argmax(logits, -1).astype(jnp.int32)
        dl, _ = decode_step(params, mcfg, nxt, cache, jnp.asarray(bt),
                            jnp.asarray([prompt_len], jnp.int32))
        return np.asarray(dl[0], np.float32)

    ref_logits = first_step_logits(None)
    ref_norm = float(np.sqrt(np.mean(ref_logits ** 2))) + 1e-9
    hdp = -(-mcfg.head_dim_ // 128) * 128
    mean_ctx = prompt_len + decode_tokens / 2

    rows: list[dict] = []
    ref_outs = None
    for kv, tag in ((None, "bf16"), ("fp8", "fp8"), ("int8", "int8")):
        eng = build(kv, fused=False)
        outs, dt = drive(eng)
        fused_outs, _ = drive(build(kv, fused=True))
        itemsize = eng.cache.k.dtype.itemsize
        bytes_step = int(n_requests * mean_ctx * mcfg.num_layers * 2
                         * mcfg.num_kv_heads * hdp * itemsize)
        if eng.cache.quantized:
            bytes_step += int(n_requests * -(-mean_ctx // block_size)
                              * mcfg.num_layers * 2 * mcfg.num_kv_heads * 4)
        if ref_outs is None:
            ref_outs = outs
        flat = [t for o in outs for t in o]
        flat_ref = [t for o in ref_outs for t in o]
        logits = ref_logits if kv is None else first_step_logits(kv)
        row = {
            "mode": tag,
            "decode_toks_s": round(sum(len(o) for o in outs) / dt, 2),
            "kv_bytes_per_step": bytes_step,
            "logit_rms": round(float(np.sqrt(np.mean(
                (logits - ref_logits) ** 2))) / ref_norm, 5),
            "first_token_match": all(
                o and r and o[0] == r[0] for o, r in zip(outs, ref_outs)),
            "token_identity": round(
                sum(a == b for a, b in zip(flat, flat_ref))
                / max(1, len(flat_ref)), 3),
            # Fused writes change WHERE bytes land, never WHICH bytes:
            # token-identical by construction, pinned per dtype here.
            "fused_outputs_match": fused_outs == outs,
        }
        rows.append(row)
        print(json.dumps(row), flush=True)
    return rows


if __name__ == "__main__":
    rows = main()
    ok = (all(r["fused_outputs_match"] for r in rows)
          and all(r["first_token_match"] for r in rows[1:])
          and all(r["token_identity"] >= 0.5 for r in rows[1:]))
    sys.exit(0 if ok else 1)
