#!/usr/bin/env python3
"""Metric-family ↔ docs parity check.

The north star requires the Prometheus contract to stay identical to the
reference's (docs/monitoring.md is normative: scrape_metrics.py treats the
dashboard as a schema and the doc documents every family). Every PR that
adds a family must document it, and every documented family must exist —
this script asserts both directions so tier-1 catches drift:

  1. every `llm_*` family registered by serving/metrics.py (ALL conditional
     sets on: replica pool + host cache) appears in docs/monitoring.md;
  2. every `llm_*` token in docs/monitoring.md names a registered family
     (histogram `_bucket`/`_sum`/`_count` suffixes and `llm_foo_*` wildcard
     prefixes are understood).

Exit 0 on parity, 1 with a report otherwise. Wired into tests/test_scripts.py.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

HIST_SUFFIXES = ("_bucket", "_sum", "_count")

# Tokens that match the family regex but are not metric families: service
# names from the static IP plan (tcp_* label values, prose mentions).
KNOWN_NON_FAMILIES = {"llm_backend"}


def registered_families(prefix: str = "llm") -> set[str]:
    """Family names as they appear in a scrape, with every conditional set
    (replica series, host-cache series) enabled."""
    from agentic_traffic_testing_tpu.serving.metrics import LLMMetrics

    m = LLMMetrics(prefix, include_tokens=True, num_replicas=2,
                   host_cache=True)
    fams = set()
    for fam in m.registry.collect():
        name = fam.name
        if fam.type == "counter":
            name += "_total"  # scrape-visible sample name
        fams.add(name)
    return fams


def documented_tokens(text: str, prefix: str = "llm") -> tuple[set, set]:
    """(exact family tokens, wildcard prefixes) mentioned in the doc.
    A token ending in `_` came from a `llm_foo_*` or `llm_foo_{a,b}`
    shorthand and is treated as a prefix wildcard. Tokens preceded by a
    double quote are PromQL label VALUES (e.g. dst_service="llm_backend"),
    not families."""
    tokens = set(re.findall(rf'(?<!"){prefix}_[a-z0-9_]+', text))
    tokens -= KNOWN_NON_FAMILIES
    exact = {t for t in tokens if not t.endswith("_")}
    prefixes = {t for t in tokens if t.endswith("_")}
    return exact, prefixes


def main(argv=None) -> int:
    doc_path = os.path.join(REPO, "docs", "monitoring.md")
    if argv:
        doc_path = argv[0]
    with open(doc_path) as f:
        text = f.read()
    reg = registered_families()
    exact, prefixes = documented_tokens(text)

    missing_from_docs = []
    for fam in sorted(reg):
        if fam in exact:
            continue
        if any(fam.startswith(p) for p in prefixes):
            continue
        missing_from_docs.append(fam)

    unknown_in_docs = []
    for tok in sorted(exact):
        if tok in reg:
            continue
        if any(tok.endswith(s) and tok[: -len(s)] in reg
               for s in HIST_SUFFIXES):
            continue
        unknown_in_docs.append(tok)
    for p in sorted(prefixes):
        if not any(f.startswith(p) for f in reg):
            unknown_in_docs.append(p + "*")

    ok = not missing_from_docs and not unknown_in_docs
    if missing_from_docs:
        print("registered but MISSING from docs/monitoring.md:")
        for fam in missing_from_docs:
            print(f"  {fam}")
    if unknown_in_docs:
        print("documented but NOT registered by serving/metrics.py:")
        for tok in unknown_in_docs:
            print(f"  {tok}")
    if ok:
        print(f"metric-docs parity OK: {len(reg)} families, "
              f"{len(exact)} documented tokens")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
