#!/usr/bin/env python3
"""Metric-family ↔ docs parity check — ALL exposition surfaces.

The north star requires the Prometheus contract to stay identical to the
reference's (docs/monitoring.md is normative: scrape_metrics.py treats the
dashboard as a schema and the doc documents every family). Every PR that
adds a family must document it, and every documented family must exist —
this script asserts both directions so tier-1 catches drift, across all
three surfaces:

  1. the server's `llm_*` families (serving/metrics.py, ALL conditional
     sets on: replica pool + host cache);
  2. the loadgen's `loadgen_*` families (loadgen/measure.py — the second
     exposition surface, served on its own port);
  3. the opt-in `vllm:*` compat aliases (LLM_VLLM_COMPAT_METRICS=1),
     documented in monitoring.md's alias table.

Each surface is checked both ways: registered-but-undocumented and
documented-but-unregistered both fail. Exit 0 on parity, 1 with a report
otherwise. Wired into tests/test_scripts.py.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

HIST_SUFFIXES = ("_bucket", "_sum", "_count")

# Tokens that match the family regex but are not metric families: service
# names from the static IP plan (tcp_* label values, prose mentions).
KNOWN_NON_FAMILIES = {"llm_backend"}


def _scrape_names(registry) -> set[str]:
    """Family names as they appear in a scrape (counters render their
    `_total` sample name)."""
    fams = set()
    for fam in registry.collect():
        name = fam.name
        if fam.type == "counter":
            name += "_total"  # scrape-visible sample name
        fams.add(name)
    return fams


def registered_families(prefix: str = "llm") -> tuple[set, set]:
    """(llm families, vllm compat alias families), with every conditional
    set (replica series, host-cache series, compat aliases) enabled."""
    from agentic_traffic_testing_tpu.serving.metrics import LLMMetrics

    m = LLMMetrics(prefix, include_tokens=True, num_replicas=2,
                   host_cache=True, vllm_compat=True,
                   pool_roles=("prefill", "decode", "mixed"))
    fams = _scrape_names(m.registry)
    vllm = {f for f in fams if f.startswith("vllm:")}
    return fams - vllm, vllm


def loadgen_families() -> set[str]:
    """The loadgen exposition surface's families (its own registry — a
    missing import here fails LOUDLY rather than silently skipping the
    second surface)."""
    from agentic_traffic_testing_tpu.loadgen.measure import LoadgenMetrics

    return _scrape_names(
        LoadgenMetrics(roles=("solver",), slo_classes=("interactive",))
        .registry)


def documented_tokens(text: str, prefix: str = "llm") -> tuple[set, set]:
    """(exact family tokens, wildcard prefixes) mentioned in the doc.
    A token ending in `_` came from a `llm_foo_*` or `llm_foo_{a,b}`
    shorthand and is treated as a prefix wildcard. Tokens preceded by a
    double quote are PromQL label VALUES (e.g. dst_service="llm_backend"),
    not families. A leading word-boundary guard keeps `llm_*` tokens from
    matching inside `vllm:*` alias names."""
    tokens = set(re.findall(rf'(?<!["a-z0-9_:]){prefix}_[a-z0-9_]+', text))
    tokens -= KNOWN_NON_FAMILIES
    exact = {t for t in tokens if not t.endswith("_")}
    prefixes = {t for t in tokens if t.endswith("_")}
    return exact, prefixes


def documented_vllm_tokens(text: str) -> set[str]:
    return set(re.findall(r"vllm:[a-z0-9_]+", text))


def check_surface(reg: set, exact: set, prefixes: set,
                  surface: str) -> tuple[list, list]:
    missing_from_docs = []
    for fam in sorted(reg):
        if fam in exact:
            continue
        if any(fam.startswith(p) for p in prefixes):
            continue
        missing_from_docs.append(f"[{surface}] {fam}")

    unknown_in_docs = []
    for tok in sorted(exact):
        if tok in reg:
            continue
        if any(tok.endswith(s) and tok[: -len(s)] in reg
               for s in HIST_SUFFIXES):
            continue
        unknown_in_docs.append(f"[{surface}] {tok}")
    for p in sorted(prefixes):
        if not any(f.startswith(p) for f in reg):
            unknown_in_docs.append(f"[{surface}] {p}*")
    return missing_from_docs, unknown_in_docs


def main(argv=None) -> int:
    doc_path = os.path.join(REPO, "docs", "monitoring.md")
    if argv:
        doc_path = argv[0]
    with open(doc_path) as f:
        text = f.read()

    llm_reg, vllm_reg = registered_families()
    lg_reg = loadgen_families()

    missing_from_docs: list[str] = []
    unknown_in_docs: list[str] = []
    for surface, reg, (exact, prefixes) in (
            ("llm", llm_reg, documented_tokens(text, "llm")),
            ("loadgen", lg_reg, documented_tokens(text, "loadgen")),
            ("vllm", vllm_reg, (documented_vllm_tokens(text), set()))):
        miss, unk = check_surface(reg, exact, prefixes, surface)
        missing_from_docs.extend(miss)
        unknown_in_docs.extend(unk)

    ok = not missing_from_docs and not unknown_in_docs
    if missing_from_docs:
        print("registered but MISSING from docs/monitoring.md:")
        for fam in missing_from_docs:
            print(f"  {fam}")
    if unknown_in_docs:
        print("documented but NOT registered:")
        for tok in unknown_in_docs:
            print(f"  {tok}")
    if ok:
        print(f"metric-docs parity OK: {len(llm_reg)} llm + {len(lg_reg)} "
              f"loadgen + {len(vllm_reg)} vllm families")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
