#!/usr/bin/env python3
"""Micro-bench the ragged hybrid-batch attention kernel at bench shapes.

A/B for the tentpole fusion claim at the KERNEL level, isolated from the
engine (same xplane device-plane methodology as paged_decode_ab.py):

  A (fused):  ONE ragged_paged_attention call over B decode rows + one
              C-token prefill-chunk row — the hybrid step's shape.
  B (serial): the dma2 decode kernel over the B decode rows, PLUS a
              second ragged call for the chunk row alone — the two
              dispatches the serial engine pays.

The fused call should win on dispatch count and by overlapping the
decode rows' page DMA with the chunk's MXU work across the shared grid;
numbers feed docs/BENCHMARKS.md once measured on hardware.

Usage: python scripts/dev/hybrid_ab.py [ctx] [batch] [chunk] [block_size]
Env: HYBRID_AB_QBLK (q tokens per kernel block, default 8).
No reference analog (the reference delegates batching policy to vLLM).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
import jax.numpy as jnp

from scripts.dev.quant_ab import device_total_ms

N = 8


def main() -> None:
    argv = [int(a) for a in sys.argv[1:]]
    ctx = argv[0] if len(argv) > 0 else 150
    b = argv[1] if len(argv) > 1 else 32
    chunk = argv[2] if len(argv) > 2 else 128
    qblk = int(os.environ.get("HYBRID_AB_QBLK", "8"))

    from agentic_traffic_testing_tpu.ops.pallas.paged_attention import (
        paged_attention_decode_dma2,
    )
    from agentic_traffic_testing_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention,
    )

    # bench.py 1B layout: 16 layers, 8 kv heads, blocks of 16, hd lane-
    # padded to 128 (real head_dim 64); pool token capacity 8192.
    L, KH, BS, HD = 16, 8, 16, 128
    BS = argv[3] if len(argv) > 3 else BS
    NB = 8192 // BS
    H = 32
    chunk_start = 256  # chunk row's prior context
    print(f"devices: {jax.devices()}  ctx={ctx} B={b} chunk={chunk} "
          f"qblk={qblk} pool=[{L},{KH},{NB},{BS},{HD}]", flush=True)

    rows = b + 1
    max_blocks = NB // rows
    assert (ctx + BS - 1) // BS <= max_blocks
    assert (chunk_start + chunk + BS - 1) // BS <= max_blocks

    key = jax.random.key(0)
    kp = jax.random.normal(key, (L, KH, NB, BS, HD), jnp.bfloat16)
    vp = jax.random.normal(key, (L, KH, NB, BS, HD), jnp.bfloat16)
    bt = jnp.arange(rows * max_blocks, dtype=jnp.int32).reshape(
        rows, max_blocks) % NB
    dec_pos = jnp.full((b,), ctx - 1, jnp.int32)
    pos = jnp.concatenate([dec_pos, jnp.asarray([chunk_start], jnp.int32)])
    q_lens = (1,) * b + (chunk,)
    t = b + chunk
    lay = jnp.int32(3)
    qs = [jax.random.normal(jax.random.key(i), (t, H, HD), jnp.bfloat16)
          for i in range(N)]

    def fused(q):
        return ragged_paged_attention(
            q, kp, vp, bt, pos, q_lens, layer=lay,
            q_tokens_per_block=qblk)

    def serial(q):
        dec = paged_attention_decode_dma2(
            q[:b], kp, vp, bt[:b], dec_pos + 1, layer=lay)
        ck = ragged_paged_attention(
            q[b:], kp, vp, bt[b:], pos[b:], (chunk,), layer=lay,
            q_tokens_per_block=qblk)
        return dec, ck

    ms_f = device_total_ms(fused, [(q,) for q in qs], "/tmp/hybrid_ab_fused")
    ms_s = device_total_ms(serial, [(q,) for q in qs], "/tmp/hybrid_ab_serial")
    print(f"  fused  (1 ragged call, {t} q tokens): {ms_f * 1e3:8.1f} us/call "
          f"DEVICE", flush=True)
    print(f"  serial (dma2 decode + chunk call):    {ms_s * 1e3:8.1f} us/call "
          f"DEVICE  ({ms_s / max(ms_f, 1e-9):.2f}x fused)", flush=True)


if __name__ == "__main__":
    main()
