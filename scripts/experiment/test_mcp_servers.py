#!/usr/bin/env python3
"""Smoke-exercise the three stdio MCP servers through the real client
(reference: scripts/experiment/test_mcp_servers.py:23-63). CI covers the
same path in tests/test_tools.py; this script is the operator-facing probe.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from agentic_traffic_testing_tpu.agents.common.mcp_client import (  # noqa: E402
    MCPClientManager,
)


async def main() -> int:
    mgr = MCPClientManager()
    print("[mcp-smoke] connecting to coding/finance/maps servers...")
    await mgr.connect_all()
    failures = 0
    try:
        for server, tools in (await mgr.list_tools()).items():
            print(f"  {server}: {[t['name'] for t in tools]}")
        checks = [
            ("coding", "execute_python_code", {"code": "print(2**10)"},
             lambda o: json.loads(o)["stdout"].strip() == "1024"),
            ("coding", "analyze_code_complexity",
             {"code": "def f():\n    if 1:\n        return 2"},
             lambda o: json.loads(o)["definitions"] == 1),
            ("finance", "get_stock_price", {"symbol": "STARK"},
             lambda o: json.loads(o)["synthetic"] is True),
            ("finance", "calculate_portfolio_value",
             {"symbols": ["ACME", "WAYNE"], "shares": [10, 2]},
             lambda o: json.loads(o)["total_value"] > 0),
            ("maps", "geocode_location", {"location": "berlin"},
             lambda o: abs(json.loads(o)["lat"] - 52.52) < 0.01),
            ("maps", "calculate_distance",
             {"origin": "rome", "destination": "london"},
             lambda o: 1300 < json.loads(o)["distance_km"] < 1600),
        ]
        for server, tool, arguments, check in checks:
            try:
                out = await mgr.call_tool(server, tool, arguments)
                ok = check(out)
            except Exception as e:
                ok, out = False, f"{type(e).__name__}: {e}"
            print(f"  [{'PASS' if ok else 'FAIL'}] {server}.{tool}")
            if not ok:
                print(f"         -> {out[:200]}")
                failures += 1
    finally:
        await mgr.close_all()
    print(f"[mcp-smoke] {'all green' if not failures else f'{failures} failures'}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))
