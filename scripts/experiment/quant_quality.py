#!/usr/bin/env python3
"""Quantization output-quality fixture (round 5).

The reference serves real Llama-3.1-8B-Instruct weights
(reference: llm/serve_llm.py:52), so its quantization quality is
observable in production traffic. This environment has zero egress and no
HF checkpoints on disk (docs/BENCHMARKS.md), so random-init weights were
the only thing quantization had ever been run on — and random weights
cannot show OUTPUT-quality deltas (their logits are noise either way).

This script builds the strongest in-environment stand-in: it trains the
in-repo byte-level model (models/config.py `tiny`, whose vocab is the
ByteTokenizer's by design) on the repository's own documentation until the
weights have real structure (loss well below uniform ~log 262 = 5.57),
then measures every quantization scheme the framework ships against the
fp32 baseline on HELD-OUT text:

  - logit RMS drift and next-token top-1 agreement,
  - held-out perplexity per scheme,
  - greedy 32-token continuation agreement through the REAL engine
    (serving path, not just forward math),
  - fp8 KV pages (LLM_KV_CACHE_DTYPE=fp8) the same way — its error enters
    through the cache, not the weights, so only the engine path shows it.

Usage:
    JAX_PLATFORMS=cpu python scripts/experiment/quant_quality.py \
        [--steps 400] [--model tiny] [--out docs/quant_quality_fixture.md]

The committed fixture numbers live in docs/BENCHMARKS.md ("Quantization
output quality"); rerun this script to reproduce them. `tests/
test_e2e_weights.py` remains the real-checkpoint E2E gate the moment
ATT_E2E_WEIGHTS_PATH points at an HF dir.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
sys.path.insert(0, REPO)

from agentic_traffic_testing_tpu.platform_guard import force_cpu_if_requested


def _corpus_ids(tok) -> list[int]:
    """The repo's own documentation as one token stream."""
    paths = [os.path.join(REPO, "README.md"), os.path.join(REPO, "SURVEY.md")]
    docs_dir = os.path.join(REPO, "docs")
    paths += sorted(
        os.path.join(docs_dir, p) for p in os.listdir(docs_dir)
        if p.endswith(".md"))
    text = "\n\n".join(
        open(p, encoding="utf-8", errors="replace").read() for p in paths
        if os.path.isfile(p))
    return tok.encode(text)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="tiny")
    ap.add_argument("--steps", type=int, default=400)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--k-group", type=int, default=64)
    ap.add_argument("--gen-prompts", type=int, default=16)
    ap.add_argument("--gen-tokens", type=int, default=32)
    ap.add_argument("--out", default=None,
                    help="write the markdown table + JSON line here")
    args = ap.parse_args()

    force_cpu_if_requested()
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from agentic_traffic_testing_tpu.models.config import resolve_config
    from agentic_traffic_testing_tpu.models.llama import forward_full
    from agentic_traffic_testing_tpu.models.quant import (
        quantize_array,
        quantize_params,
    )
    from agentic_traffic_testing_tpu.parallel.mesh import make_mesh
    from agentic_traffic_testing_tpu.runtime.engine import (
        EngineConfig,
        LLMEngine,
    )
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams
    from agentic_traffic_testing_tpu.training.train import (
        init_train_state,
        make_train_step,
    )
    from agentic_traffic_testing_tpu.utils.tokenizer import load_tokenizer

    cfg = resolve_config(args.model)
    tok = load_tokenizer("byte-fallback")
    if cfg.vocab_size < tok.vocab_size:
        raise SystemExit(f"{args.model}: vocab {cfg.vocab_size} < byte "
                         f"tokenizer {tok.vocab_size}")
    ids = _corpus_ids(tok)
    split = int(len(ids) * 0.9)
    train_ids = np.asarray(ids[:split], np.int32)
    held_ids = np.asarray(ids[split:], np.int32)
    print(f"corpus: {len(ids)} tokens ({split} train / {len(held_ids)} held)",
          flush=True)

    # ---- train ----------------------------------------------------------
    mesh = make_mesh()
    optimizer = optax.adamw(args.lr)
    params, opt_state = init_train_state(cfg, mesh, optimizer,
                                         seed=args.seed, dtype=jnp.float32)
    step = make_train_step(cfg, mesh, optimizer)
    rng = np.random.default_rng(args.seed)
    t0 = time.time()
    loss = float("nan")
    for i in range(args.steps):
        starts = rng.integers(0, len(train_ids) - args.seq - 1, args.batch)
        tokens = np.stack([train_ids[s:s + args.seq] for s in starts])
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(tokens),
            jnp.ones_like(tokens, jnp.float32))
        if i % 50 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {float(loss):.4f} "
                  f"({time.time() - t0:.0f}s)", flush=True)
    final_loss = float(loss)
    if final_loss > 4.5:
        print(f"WARNING: final loss {final_loss:.2f} is close to uniform "
              f"(5.57) — the fixture is weak; raise --steps", flush=True)

    # ---- held-out evaluation -------------------------------------------
    n_eval = min(16, (len(held_ids) - 1) // args.seq)
    eval_tokens = jnp.asarray(np.stack(
        [held_ids[i * args.seq:(i + 1) * args.seq] for i in range(n_eval)]))
    eval_targets = jnp.asarray(np.stack(
        [held_ids[i * args.seq + 1:(i + 1) * args.seq + 1]
         for i in range(n_eval)]))

    def eval_metrics(p):
        logits = np.asarray(forward_full(p, cfg, eval_tokens), np.float32)
        logp = logits - np.log(np.exp(
            logits - logits.max(-1, keepdims=True)).sum(-1, keepdims=True)) \
            - logits.max(-1, keepdims=True)
        nll = -np.take_along_axis(
            logp, np.asarray(eval_targets)[..., None], axis=-1).mean()
        return logits, float(np.exp(nll))

    base_logits, base_ppl = eval_metrics(params)
    base_top1 = base_logits.argmax(-1)

    def scheme_variants():
        yield "int8", quantize_params(params, scheme="int8")
        yield "int4", quantize_params(params, scheme="int4")
        q_kg = quantize_params(params, scheme="int4",
                               int4_k_group=args.k_group)
        yield f"int4 kg={args.k_group}", q_kg

    rows = []
    for name, qp in scheme_variants():
        logits, ppl = eval_metrics(qp)
        rms = float(np.sqrt(((logits - base_logits) ** 2).mean()))
        ref_rms = float(np.sqrt((base_logits ** 2).mean()))
        top1 = float((logits.argmax(-1) == base_top1).mean())
        rows.append({"scheme": name, "ppl": ppl,
                     "logit_rms_rel": rms / ref_rms, "top1_agree": top1})
        print(f"{name}: ppl {ppl:.3f} (base {base_ppl:.3f}), rel logit RMS "
              f"{rms / ref_rms:.4f}, top-1 agree {top1:.4f}", flush=True)

    # ---- greedy continuation agreement through the real engine ----------
    samp = SamplingParams(temperature=0.0, max_tokens=args.gen_tokens,
                          ignore_eos=True)
    prompts = []
    for i in range(args.gen_prompts):
        s = rng.integers(0, max(1, len(held_ids) - 64))
        prompts.append([int(t) for t in held_ids[s:s + 48]])

    def engine_outputs(p=None, quantization=None, kv_cache_dtype=None,
                       k_group=0):
        ecfg = EngineConfig(model=args.model, dtype="float32",
                            quantization=quantization,
                            int4_k_group=k_group,
                            kv_cache_dtype=kv_cache_dtype,
                            num_blocks=128, max_model_len=128)
        eng = LLMEngine(ecfg, model_cfg=cfg,
                        params=p if p is not None else params)
        return [eng.generate(pr, samp).output_ids for pr in prompts]

    base_gen = engine_outputs()

    def gen_agreement(gen) -> tuple[float, float]:
        """(exact-sequence rate, mean matching-prefix fraction)."""
        exact = np.mean([g == b for g, b in zip(gen, base_gen)])
        fracs = []
        for g, b in zip(gen, base_gen):
            n = 0
            for x, y in zip(g, b):
                if x != y:
                    break
                n += 1
            fracs.append(n / max(1, len(b)))
        return float(exact), float(np.mean(fracs))

    gen_rows = []
    for name, quant, kg in [("int8", "int8", 0), ("int4", "int4", 0),
                            (f"int4 kg={args.k_group}", "int4",
                             args.k_group)]:
        qp = quantize_params(params, scheme=quant, int4_k_group=kg)
        exact, frac = gen_agreement(engine_outputs(
            p=qp, quantization=quant, k_group=kg))
        gen_rows.append({"scheme": name, "gen_exact": exact,
                         "gen_prefix_frac": frac})
        print(f"{name}: greedy {args.gen_tokens}-token exact-match "
              f"{exact:.3f}, mean matching prefix {frac:.3f}", flush=True)

    exact8, frac8 = gen_agreement(engine_outputs(kv_cache_dtype="fp8"))
    gen_rows.append({"scheme": "fp8 KV (fp32 weights)", "gen_exact": exact8,
                     "gen_prefix_frac": frac8})
    print(f"fp8 KV: greedy exact-match {exact8:.3f}, mean matching prefix "
          f"{frac8:.3f}", flush=True)

    # ---- report ---------------------------------------------------------
    by_scheme = {r["scheme"]: r for r in rows}
    lines = [
        "| scheme | held-out ppl | rel logit RMS | top-1 agree | "
        f"greedy {args.gen_tokens}-tok exact | mean matching prefix |",
        "|---|---|---|---|---|---|",
        f"| fp32 baseline | {base_ppl:.3f} | 0 | 1.000 | 1.000 | 1.000 |",
    ]
    for gr in gen_rows:
        r = by_scheme.get(gr["scheme"], {})
        ppl = f"{r['ppl']:.3f}" if r else "= baseline"
        rms = f"{r['logit_rms_rel']:.4f}" if r else "n/a (cache-side)"
        top1 = f"{r['top1_agree']:.4f}" if r else "n/a"
        lines.append(
            f"| {gr['scheme']} | {ppl} | {rms} | {top1} | "
            f"{gr['gen_exact']:.3f} | {gr['gen_prefix_frac']:.3f} |")
    table = "\n".join(lines)
    print("\n" + table, flush=True)
    record = {
        "model": args.model, "steps": args.steps, "final_loss": final_loss,
        "base_ppl": base_ppl, "rows": rows, "gen_rows": gen_rows,
        "corpus_tokens": len(ids),
    }
    if args.out:
        with open(args.out, "w") as f:
            f.write("# Quantization output quality — trained byte-LM "
                    "fixture\n\n")
            f.write(f"Generated by scripts/experiment/quant_quality.py "
                    f"(model={args.model}, steps={args.steps}, final train "
                    f"loss {final_loss:.3f}, corpus {len(ids)} tokens of "
                    f"in-repo docs).\n\n")
            f.write(table + "\n\n```json\n" + json.dumps(record) + "\n```\n")
        print(f"wrote {args.out}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
