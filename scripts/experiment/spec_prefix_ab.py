#!/usr/bin/env python3
"""Controlled A/B: ngram speculation x prefix caching (round-1 anomaly).

Round-1 full-stack numbers showed fanout throughput of 221 tok/s with
speculation alone but 80 tok/s with prefix-caching+speculation — a 2.7x
swing attributed to "tunnel drift", which drift cannot explain. This script
isolates the interaction at the engine level: the agent-b fan-out shape
(requests sharing a long system-prompt prefix, arriving concurrently),
2x2 {speculation} x {prefix caching}, BENCH_REPS repetitions each,
reporting median throughput, speculation acceptance
(spec_emitted/spec_iters), and the prefill-path split (batched vs solo
chunk admissions — the suspected mechanism: cache-hit requests admit solo,
tearing down the decode pipeline per admission).

Usage:  python scripts/experiment/spec_prefix_ab.py [--model llama-3.2-1b]
Prints one markdown table + one JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def run_case(model: str, *, spec: bool, prefix: bool, reps: int,
             fanout: int, prefix_len: int, suffix_len: int,
             decode_tokens: int):
    import numpy as np

    from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams

    cfg = EngineConfig(
        model=model, dtype="bfloat16",
        max_num_seqs=fanout,
        max_model_len=max(1024, prefix_len + suffix_len + decode_tokens + 16),
        prefix_caching=prefix,
        speculation="ngram" if spec else None,
    )
    engine = LLMEngine(cfg)
    rng = np.random.default_rng(0)
    vocab = engine.model_cfg.vocab_size
    # Repetitive alphabet -> n-gram proposals can actually hit; shared
    # prefix -> the prefix cache can actually hit (the agentic shape).
    alphabet = rng.integers(10, 200, 24).tolist()
    shared = [alphabet[i % len(alphabet)] for i in range(prefix_len)]

    counts = {"prefill": 0, "chunk": 0}
    orig_prefill, orig_chunk = engine._run_prefill, engine._run_chunk

    def cp(plan):
        counts["prefill"] += 1
        return orig_prefill(plan)

    def cc(plan):
        counts["chunk"] += 1
        return orig_chunk(plan)

    engine._run_prefill, engine._run_chunk = cp, cc

    def one_wave():
        reqs = []
        for i in range(fanout):
            suffix = [alphabet[(i + j) % len(alphabet)] for j in range(suffix_len)]
            reqs.append(engine.add_request(
                shared + suffix,
                SamplingParams(temperature=0.0, max_tokens=decode_tokens,
                               ignore_eos=True)))
        t0 = time.monotonic()
        while engine.has_work() and not all(r.is_finished() for r in reqs):
            engine.step()
        dt = time.monotonic() - t0
        return sum(len(r.output_ids) for r in reqs) / dt

    one_wave()  # warmup: compiles + seeds the prefix cache
    counts["prefill"] = counts["chunk"] = 0
    vals = [one_wave() for _ in range(reps)]
    accept = (engine.spec_emitted / engine.spec_iters
              if engine.spec_iters else None)
    return {
        "spec": spec, "prefix": prefix,
        "toks_s_median": round(statistics.median(vals), 1),
        "toks_s_spread": [round(min(vals), 1), round(max(vals), 1)],
        "accept_tok_per_iter": round(accept, 3) if accept else None,
        "prefills_batched": counts["prefill"],
        "prefills_solo_chunks": counts["chunk"],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--fanout", type=int, default=5)
    ap.add_argument("--prefix-len", type=int, default=384)
    ap.add_argument("--suffix-len", type=int, default=32)
    ap.add_argument("--decode-tokens", type=int, default=64)
    args = ap.parse_args()

    import jax

    platform = jax.devices()[0].platform
    model = args.model or ("llama-3.2-1b" if platform == "tpu" else "debug-512")

    rows = []
    for spec in (False, True):
        for prefix in (False, True):
            r = run_case(model, spec=spec, prefix=prefix, reps=args.reps,
                         fanout=args.fanout, prefix_len=args.prefix_len,
                         suffix_len=args.suffix_len,
                         decode_tokens=args.decode_tokens)
            rows.append(r)
            print(f"  done spec={spec} prefix={prefix}: "
                  f"{r['toks_s_median']} tok/s", file=sys.stderr)

    print("| spec | prefix | tok/s (median) | spread | accept tok/iter | "
          "batched prefills | solo chunks |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        print(f"| {'on' if r['spec'] else 'off'} | "
              f"{'on' if r['prefix'] else 'off'} | {r['toks_s_median']} | "
              f"{r['toks_s_spread']} | {r['accept_tok_per_iter'] or '—'} | "
              f"{r['prefills_batched']} | {r['prefills_solo_chunks']} |")
    print(json.dumps({"model": model, "platform": platform,
                      "fanout": args.fanout, "reps": args.reps, "rows": rows}))


if __name__ == "__main__":
    main()
