#!/usr/bin/env bash
# Batch AgentVerse experiment runner with resume support.
#
# Rebuild of the reference runner (reference:
# scripts/experiment/run_experiment.sh:12-580): loads tasks from the workflow
# template's example_tasks, POSTs each to Agent A /agentverse, persists
# response.json/meta.json per run, scrapes Prometheus per-run and in
# aggregate, and renders plots. Crash-resumable: position is reconstructed
# from runs.jsonl on `-c`.
#
# Usage:
#   run_experiment.sh -n 3                 # 3 iterations over all tasks
#   run_experiment.sh -n 3 -t plan-city-network   # one task only
#   run_experiment.sh -c <experiment_dir>  # resume an interrupted batch
set -u

SCRIPT_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
REPO_ROOT="$(cd "$SCRIPT_DIR/../.." && pwd)"
TEMPLATE="${TEMPLATE:-$REPO_ROOT/agentic_traffic_testing_tpu/agents/templates/agentverse_workflow.json}"
AGENT_A_URL="${AGENT_A_URL:-http://localhost:8101}"
EXPERIMENTS_DIR="${EXPERIMENTS_DIR:-$REPO_ROOT/data/experiments}"
WAIT_AFTER_RUN="${WAIT_AFTER_RUN:-5}"
REQUEST_TIMEOUT="${REQUEST_TIMEOUT:-600}"
SCRAPE="${SCRAPE:-1}"

ITERATIONS=1
TASK_FILTER=""
RESUME_DIR=""

usage() { grep '^#' "$0" | sed 's/^# \{0,1\}//'; exit 1; }

while getopts "n:t:c:h" opt; do
  case "$opt" in
    n) ITERATIONS="$OPTARG" ;;
    t) TASK_FILTER="$OPTARG" ;;
    c) RESUME_DIR="$OPTARG" ;;
    h|*) usage ;;
  esac
done

command -v curl >/dev/null || { echo "curl required" >&2; exit 2; }
command -v python3 >/dev/null || { echo "python3 required" >&2; exit 2; }

# ---------------------------------------------------------------- task list
load_tasks_from_template() {
  python3 - "$TEMPLATE" "$TASK_FILTER" <<'EOF'
import json, sys
tmpl, flt = sys.argv[1], sys.argv[2]
with open(tmpl) as f:
    tasks = json.load(f)["example_tasks"]
for t in tasks:
    if not flt or t["task_id"] == flt:
        print(json.dumps(t))
EOF
}

# ---------------------------------------------------------------- experiment dir
if [ -n "$RESUME_DIR" ]; then
  EXP_DIR="$RESUME_DIR"
  [ -d "$EXP_DIR" ] || { echo "no such experiment dir: $EXP_DIR" >&2; exit 2; }
  ITERATIONS="$(cat "$EXP_DIR/iterations.txt" 2>/dev/null || echo "$ITERATIONS")"
  echo "[exp] resuming $EXP_DIR (iterations=$ITERATIONS)"
else
  STAMP="$(date +%Y%m%d_%H%M%S)"
  EXP_DIR="$EXPERIMENTS_DIR/${STAMP}_agentverse"
  mkdir -p "$EXP_DIR"
  echo "$ITERATIONS" > "$EXP_DIR/iterations.txt"
  echo "[exp] new experiment -> $EXP_DIR"
fi
RUNS_JSONL="$EXP_DIR/runs.jsonl"
SUMMARY="$EXP_DIR/summary.txt"
touch "$RUNS_JSONL"

already_done() {  # $1 = run key "iter/task_id"
  grep -q "\"run_key\": \"$1\"" "$RUNS_JSONL" 2>/dev/null
}

# ---------------------------------------------------------------- one run
send_agentverse_request() {  # $1 iter  $2 task_id  $3 task json
  local iter="$1" task_id="$2" task_json="$3"
  local run_key="${iter}/${task_id}"
  local run_dir="$EXP_DIR/$(date +%s)_${task_id}_${iter}"
  mkdir -p "$run_dir"
  local t0 t1 status
  t0="$(date +%s.%N)"
  status="$(curl -s -m "$REQUEST_TIMEOUT" -o "$run_dir/response.json" \
      -w '%{http_code}' -X POST "$AGENT_A_URL/agentverse" \
      -H 'Content-Type: application/json' \
      -d "$(python3 -c 'import json,sys; t=json.loads(sys.argv[1]); print(json.dumps({"task": t["task"], "task_id": t["task_id"]+"-i'"$iter"'"}))' "$task_json")")"
  t1="$(date +%s.%N)"
  python3 - "$run_dir" "$run_key" "$status" "$t0" "$t1" <<'EOF'
import json, sys
run_dir, run_key, status, t0, t1 = sys.argv[1:6]
meta = {"run_key": run_key, "http_status": int(status or 0),
        "started": float(t0), "finished": float(t1),
        "wall_s": round(float(t1) - float(t0), 3)}
try:
    with open(f"{run_dir}/response.json") as f:
        resp = json.load(f)
    ev = resp.get("evaluation", {})
    meta.update(task_id=resp.get("task_id"),
                iterations=resp.get("iteration_count"),
                score=ev.get("overall_score"),
                goal_achieved=ev.get("goal_achieved"),
                llm_calls=(resp.get("aggregates") or {}).get("num_llm_calls"))
except Exception as e:
    meta["parse_error"] = str(e)
with open(f"{run_dir}/meta.json", "w") as f:
    json.dump(meta, f, indent=2)
print(json.dumps(meta))
EOF
}

# ---------------------------------------------------------------- loop
TASKS="$(load_tasks_from_template)"
[ -n "$TASKS" ] || { echo "no tasks matched" >&2; exit 2; }
TOTAL=0; OK=0; SKIPPED=0

for iter in $(seq 1 "$ITERATIONS"); do
  while IFS= read -r task_json; do
    task_id="$(python3 -c 'import json,sys; print(json.loads(sys.argv[1])["task_id"])' "$task_json")"
    run_key="${iter}/${task_id}"
    if already_done "$run_key"; then
      SKIPPED=$((SKIPPED+1)); continue
    fi
    echo "[exp] run $run_key"
    TOTAL=$((TOTAL+1))
    window_start="$(date +%s)"
    meta_line="$(send_agentverse_request "$iter" "$task_id" "$task_json")"
    echo "$meta_line" >> "$RUNS_JSONL"
    http_status="$(python3 -c 'import json,sys; print(json.loads(sys.argv[1])["http_status"])' "$meta_line")"
    [ "$http_status" = "200" ] && OK=$((OK+1))
    sleep "$WAIT_AFTER_RUN"   # let metrics propagate before the window closes
    if [ "$SCRAPE" = "1" ]; then
      last_run_dir="$(ls -dt "$EXP_DIR"/*_"$task_id"_"$iter" 2>/dev/null | head -1)"
      python3 "$SCRIPT_DIR/scrape_metrics.py" \
        --start "$window_start" --end "$(date +%s)" \
        --out "$last_run_dir/metrics.csv" 2>/dev/null || true
    fi
  done <<< "$TASKS"
done

# ---------------------------------------------------------------- finalize
finalize_experiment() {
  {
    echo "experiment: $EXP_DIR"
    echo "finished:   $(date -Is)"
    echo "runs total=$TOTAL ok=$OK skipped(resume)=$SKIPPED"
  } > "$SUMMARY"
  if [ "$SCRAPE" = "1" ]; then
    first="$(python3 -c 'import json,sys
rows=[json.loads(l) for l in open(sys.argv[1])]
print(min(r["started"] for r in rows) if rows else "")' "$RUNS_JSONL")"
    if [ -n "$first" ]; then
      python3 "$SCRIPT_DIR/scrape_metrics.py" --start "$first" \
        --end "$(date +%s)" --out "$EXP_DIR/metrics.csv" 2>/dev/null || true
    fi
  fi
  python3 "$SCRIPT_DIR/plot_results.py" --experiment-dir "$EXP_DIR" || true
  echo DONE > "$EXP_DIR/DONE"
  cat "$SUMMARY"
}
finalize_experiment
