#!/usr/bin/env bash
# Experiment watchdog (reference: scripts/experiment/monitor_experiment.sh):
# if the newest experiment has no DONE marker and no live runner process,
# restart run_experiment.sh in resume mode (-c) on that directory.
set -u
SCRIPT_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
REPO_ROOT="$(cd "$SCRIPT_DIR/../.." && pwd)"
EXPERIMENTS_DIR="${EXPERIMENTS_DIR:-$REPO_ROOT/data/experiments}"

latest="$(ls -dt "$EXPERIMENTS_DIR"/*_agentverse 2>/dev/null | head -1)"
[ -n "$latest" ] || exit 0
[ -f "$latest/DONE" ] && exit 0

if pgrep -f "run_experiment.sh" >/dev/null 2>&1; then
  exit 0  # still running
fi

echo "[watchdog] $(date -Is) detected crashed experiment $latest — resuming"
nohup "$SCRIPT_DIR/run_experiment.sh" -c "$latest" \
  >> "${LOG:-/tmp/agentic_experiment.log}" 2>&1 &
