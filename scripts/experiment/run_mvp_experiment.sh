#!/usr/bin/env bash
# Legacy MVP scenario driver (reference: scripts/experiment/run_mvp_experiment.sh).
# Fires N /task requests per scenario against Agent A — smoke-level load
# without the AgentVerse machinery. Superseded by run_experiment.sh for real
# experiments; kept for quick backend/agent shakeouts.
set -u

AGENT_A_URL="${AGENT_A_URL:-http://localhost:8101}"
N="${1:-3}"
SCENARIOS=(${SCENARIOS:-agentic_simple agentic_parallel})
OUT_DIR="${OUT_DIR:-data/mvp/$(date +%Y%m%d_%H%M%S)}"
mkdir -p "$OUT_DIR"

echo "[mvp] $N iterations x scenarios: ${SCENARIOS[*]} -> $OUT_DIR"
ok=0; fail=0
for i in $(seq 1 "$N"); do
  for sc in "${SCENARIOS[@]}"; do
    out="$OUT_DIR/run_${i}_${sc}.json"
    status=$(curl -s -m 300 -o "$out" -w "%{http_code}" \
      -H "Content-Type: application/json" \
      -d "{\"task\": \"Summarize the tradeoffs of paged attention (run $i)\", \"scenario\": \"$sc\"}" \
      "$AGENT_A_URL/task" || echo 000)
    if [ "$status" = 200 ]; then
      ok=$((ok+1)); echo "[mvp] $i/$sc ok"
    else
      fail=$((fail+1)); echo "[mvp] $i/$sc FAILED http=$status" >&2
    fi
    sleep "${WAIT_BETWEEN_RUNS:-2}"
  done
done

echo "[mvp] done: $ok ok, $fail failed (outputs in $OUT_DIR)"
[ "$fail" = 0 ]
