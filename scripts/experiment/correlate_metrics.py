#!/usr/bin/env python3
"""Join per-task app metrics (llm_calls.jsonl) with per-task TCP metrics.

Rebuild of the reference correlator (reference:
scripts/experiment/correlate_metrics.py:118-406): for each task id found in
`logs/llm_calls.jsonl`, compute its time window, run Prometheus `increase()`
queries over that window for the TCP edges involving the LLM and the agents,
and emit one CSV row per task joining the app view (calls, tokens, latency)
with the network view (bytes to/from the LLM, agent A->B bytes, SYN counts,
RTT quantiles).

Output: data/correlated.csv
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import sys
import urllib.parse
import urllib.request
from collections import defaultdict
from typing import Any, Dict, List, Optional

TCP_QUERIES = {
    "tcp_bytes_to_llm":
        'sum(increase(tcp_bytes_total{{dst_service="llm_backend"}}[{w}s] @ {end}))',
    "tcp_bytes_from_llm":
        'sum(increase(tcp_bytes_total{{src_service="llm_backend"}}[{w}s] @ {end}))',
    "tcp_bytes_a_to_b":
        'sum(increase(tcp_bytes_total{{src_service="agent_a",dst_service=~"agent_b.*"}}[{w}s] @ {end}))',
    "tcp_syn_count":
        'sum(increase(tcp_syn_total[{w}s] @ {end}))',
    "tcp_rtt_p50_s":
        'histogram_quantile(0.5, sum(increase(tcp_rtt_handshake_seconds_bucket[{w}s] @ {end})) by (le))',
    "tcp_rtt_p95_s":
        'histogram_quantile(0.95, sum(increase(tcp_rtt_handshake_seconds_bucket[{w}s] @ {end})) by (le))',
}


def query_scalar(prom_url: str, expr: str) -> Optional[float]:
    params = urllib.parse.urlencode({"query": expr})
    try:
        with urllib.request.urlopen(f"{prom_url}/api/v1/query?{params}",
                                    timeout=15) as resp:
            payload = json.loads(resp.read())
        result = payload.get("data", {}).get("result", [])
        if not result:
            return None
        return float(result[0]["value"][1])
    except Exception as e:
        print(f"[correlate] query failed ({e}): {expr[:90]}", file=sys.stderr)
        return None


def load_calls(path: str) -> Dict[str, List[dict]]:
    """llm_calls.jsonl -> {task_id: [rows]} (rows without task ids dropped)."""
    tasks: Dict[str, List[dict]] = defaultdict(list)
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            tid = row.get("task_id")
            if tid:
                tasks[str(tid)].append(row)
    return tasks


def task_window(rows: List[dict], pad_s: float) -> Optional[Dict[str, float]]:
    starts = [r.get("started_at_ms") for r in rows if r.get("started_at_ms")]
    ends = [r.get("finished_at_ms") for r in rows if r.get("finished_at_ms")]
    if not starts or not ends:
        return None
    start = min(starts) / 1000.0 - pad_s
    end = max(ends) / 1000.0 + pad_s
    return {"start": start, "end": end, "window_s": max(1.0, end - start)}


def build_app_row(task_id: str, rows: List[dict]) -> Dict[str, Any]:
    def total(key: str) -> float:
        return sum(r.get(key) or 0 for r in rows)

    errors = sum(1 for r in rows if r.get("error"))
    return {
        "task_id": task_id,
        "num_llm_calls": len(rows),
        "num_errors": errors,
        "prompt_tokens": int(total("prompt_tokens")),
        "completion_tokens": int(total("completion_tokens")),
        "total_tokens": int(total("total_tokens")),
        "total_latency_ms": round(total("latency_ms"), 2),
        "agents": ",".join(sorted({str(r.get("agent_id")) for r in rows})),
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--calls", default=os.path.join(
        os.environ.get("TELEMETRY_LOG_DIR", "logs"), "llm_calls.jsonl"))
    ap.add_argument("--prometheus",
                    default=os.environ.get("PROMETHEUS_URL",
                                           "http://localhost:9090"))
    ap.add_argument("--out", default="data/correlated.csv")
    ap.add_argument("--pad-s", type=float, default=2.0,
                    help="window padding around first/last call")
    ap.add_argument("--no-prometheus", action="store_true",
                    help="emit app columns only (offline mode)")
    args = ap.parse_args(argv)

    if not os.path.isfile(args.calls):
        print(f"[correlate] no calls file at {args.calls}", file=sys.stderr)
        return 1
    tasks = load_calls(args.calls)
    if not tasks:
        print("[correlate] no task ids found", file=sys.stderr)
        return 1

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    fieldnames = ["task_id", "num_llm_calls", "num_errors", "prompt_tokens",
                  "completion_tokens", "total_tokens", "total_latency_ms",
                  "agents", "window_start", "window_end", "window_s",
                  *TCP_QUERIES.keys()]
    n = 0
    with open(args.out, "w", newline="", encoding="utf-8") as f:
        writer = csv.DictWriter(f, fieldnames=fieldnames)
        writer.writeheader()
        for task_id, rows in sorted(tasks.items()):
            row = build_app_row(task_id, rows)
            window = task_window(rows, args.pad_s)
            if window:
                row.update({"window_start": round(window["start"], 3),
                            "window_end": round(window["end"], 3),
                            "window_s": round(window["window_s"], 3)})
                if not args.no_prometheus:
                    for col, template in TCP_QUERIES.items():
                        expr = template.format(w=int(window["window_s"]),
                                               end=f"{window['end']:.3f}")
                        row[col] = query_scalar(args.prometheus.rstrip("/"),
                                                expr)
            writer.writerow(row)
            n += 1
    print(f"[correlate] wrote {n} task rows -> {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
