#!/usr/bin/env python3
"""Experiment plots + interarrival-time distribution analysis.

Rebuild of the reference plotter (reference:
scripts/experiment/plot_results.py — row plots :1-627, response-derived
arrivals :628-693, distribution fitting :866-901, descriptives :904-936,
interpretation :938-974):

  * Grafana-style PNG per metric group from the scraped metrics.csv
  * Interarrival-time (IAT) histogram + ECDF from per-run response.json
    LLM-call timestamps
  * Distribution fitting by MLE — expon, weibull_min, lognorm, gamma,
    pareto — ranked by AIC/BIC with KS statistics
  * Descriptives: CV, lag-k autocorrelation, Ljung-Box portmanteau test
  * A plain-English interpretation block (burstiness, memorylessness)

Outputs land in --out-dir: plots/*.png + iat_analysis.json + iat_report.txt.
"""

from __future__ import annotations

import argparse
import csv
import glob
import json
import math
import os
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional

import numpy as np

import matplotlib
matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

from scipy import stats  # noqa: E402

FIT_DISTRIBUTIONS = {
    "expon": stats.expon,
    "weibull": stats.weibull_min,
    "lognorm": stats.lognorm,
    "gamma": stats.gamma,
    "pareto": stats.pareto,
}


# --------------------------------------------------------------------------
# Arrival extraction
# --------------------------------------------------------------------------


def arrivals_from_responses(run_dirs: List[str]) -> List[float]:
    """Collect LLM-call start timestamps (ms) from persisted responses.

    Accepts both /task payloads (detail.steps) and /agentverse payloads
    (llm_calls with started_at via the metrics log schema); falls back to
    logs/llm_calls.jsonl rows when response files carry no timestamps.
    """
    ts: List[float] = []
    for d in run_dirs:
        for path in glob.glob(os.path.join(d, "response.json")):
            try:
                with open(path, encoding="utf-8") as f:
                    data = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            for call in data.get("llm_calls") or []:
                t = call.get("started_at_ms") or call.get("started_at")
                if t:
                    ts.append(float(t))
    return sorted(ts)


def arrivals_from_calls_log(path: str) -> List[float]:
    ts = []
    if os.path.isfile(path):
        with open(path, encoding="utf-8") as f:
            for line in f:
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if row.get("started_at_ms"):
                    ts.append(float(row["started_at_ms"]))
    return sorted(ts)


def interarrival_seconds(arrivals_ms: List[float]) -> np.ndarray:
    arr = np.asarray(arrivals_ms, dtype=float) / 1000.0
    iat = np.diff(arr)
    return iat[iat > 0]


# --------------------------------------------------------------------------
# Fitting + descriptives (reference :866-936)
# --------------------------------------------------------------------------


def fit_distributions(iat: np.ndarray) -> List[Dict[str, Any]]:
    out = []
    n = len(iat)
    for name, dist in FIT_DISTRIBUTIONS.items():
        try:
            if name in ("expon", "pareto"):
                params = dist.fit(iat, floc=0)
            else:
                params = dist.fit(iat)
            ll = float(np.sum(dist.logpdf(iat, *params)))
            k = len(params)
            ks_stat, ks_p = stats.kstest(iat, dist.cdf, args=params)
            out.append({
                "distribution": name,
                "params": [round(float(p), 6) for p in params],
                "log_likelihood": round(ll, 2),
                "aic": round(2 * k - 2 * ll, 2),
                "bic": round(k * math.log(n) - 2 * ll, 2),
                "ks_stat": round(float(ks_stat), 4),
                "ks_pvalue": round(float(ks_p), 6),
            })
        except Exception as e:
            out.append({"distribution": name, "error": f"{type(e).__name__}: {e}"})
    ranked = sorted([o for o in out if "aic" in o], key=lambda o: o["aic"])
    for i, o in enumerate(ranked):
        o["aic_rank"] = i + 1
    return out


def autocorrelation(x: np.ndarray, max_lag: int) -> List[float]:
    x = x - x.mean()
    denom = float(np.dot(x, x))
    if denom == 0:
        return [0.0] * max_lag
    return [float(np.dot(x[:-k], x[k:]) / denom) for k in range(1, max_lag + 1)]


def ljung_box(x: np.ndarray, lags: int) -> Dict[str, float]:
    n = len(x)
    acf = autocorrelation(x, lags)
    q = n * (n + 2) * sum(r * r / (n - k)
                          for k, r in enumerate(acf, start=1))
    p = 1.0 - stats.chi2.cdf(q, lags)
    return {"q_stat": round(float(q), 3), "p_value": round(float(p), 6),
            "lags": lags}


def descriptives(iat: np.ndarray) -> Dict[str, Any]:
    mean = float(iat.mean())
    std = float(iat.std(ddof=1)) if len(iat) > 1 else 0.0
    lags = min(10, max(1, len(iat) // 5))
    return {
        "n": int(len(iat)),
        "mean_s": round(mean, 4),
        "std_s": round(std, 4),
        "cv": round(std / mean, 4) if mean else None,
        "p50_s": round(float(np.percentile(iat, 50)), 4),
        "p95_s": round(float(np.percentile(iat, 95)), 4),
        "min_s": round(float(iat.min()), 5),
        "max_s": round(float(iat.max()), 4),
        "acf": [round(a, 4) for a in autocorrelation(iat, lags)],
        "ljung_box": ljung_box(iat, lags),
    }


def interpret(desc: Dict[str, Any], fits: List[Dict[str, Any]]) -> str:
    """Plain-English reading of the arrival process (reference :938-974)."""
    lines = []
    cv = desc.get("cv")
    if cv is None:
        return "Not enough interarrival samples to characterize the process."
    if cv < 0.8:
        lines.append(f"CV={cv}: arrivals are MORE regular than Poisson — "
                     "consistent with a closed loop pacing itself on LLM latency.")
    elif cv <= 1.2:
        lines.append(f"CV={cv}: arrivals look approximately Poisson "
                     "(memoryless) at this aggregation.")
    else:
        lines.append(f"CV={cv}: arrivals are BURSTY (overdispersed) — "
                     "agent fan-outs inject clustered request trains.")
    lb = desc.get("ljung_box", {})
    if lb.get("p_value", 1.0) < 0.05:
        lines.append(f"Ljung-Box p={lb['p_value']}: interarrivals are "
                     "autocorrelated — the process has memory (workflow "
                     "structure leaks into timing).")
    else:
        lines.append(f"Ljung-Box p={lb.get('p_value')}: no significant "
                     "autocorrelation detected.")
    ranked = [f for f in fits if f.get("aic_rank") == 1]
    if ranked:
        best = ranked[0]
        lines.append(f"Best-fit distribution by AIC: {best['distribution']} "
                     f"(KS={best['ks_stat']}, p={best['ks_pvalue']}).")
        if best["distribution"] != "expon":
            lines.append("A non-exponential best fit means simple Poisson "
                         "traffic generators will NOT reproduce this load.")
    return "\n".join(lines)


# --------------------------------------------------------------------------
# Plots
# --------------------------------------------------------------------------


def plot_iat(iat: np.ndarray, fits: List[Dict[str, Any]], out_dir: str) -> None:
    os.makedirs(os.path.join(out_dir, "plots"), exist_ok=True)
    fig, axes = plt.subplots(1, 2, figsize=(11, 4))
    axes[0].hist(iat, bins=min(40, max(5, len(iat) // 3)), density=True,
                 alpha=0.6, label="observed")
    xs = np.linspace(iat.min(), np.percentile(iat, 99), 200)
    for fit in fits:
        if fit.get("aic_rank") in (1, 2):
            dist = FIT_DISTRIBUTIONS[fit["distribution"]]
            axes[0].plot(xs, dist.pdf(xs, *fit["params"]),
                         label=f"{fit['distribution']} (AIC#{fit['aic_rank']})")
    axes[0].set_title("Interarrival time density")
    axes[0].set_xlabel("seconds")
    axes[0].legend(fontsize=8)

    sorted_iat = np.sort(iat)
    ecdf = np.arange(1, len(iat) + 1) / len(iat)
    axes[1].step(sorted_iat, ecdf, where="post")
    axes[1].set_title("Interarrival ECDF")
    axes[1].set_xlabel("seconds")
    fig.tight_layout()
    fig.savefig(os.path.join(out_dir, "plots", "interarrival.png"), dpi=120)
    plt.close(fig)


def plot_metric_rows(metrics_csv: str, out_dir: str) -> int:
    """One PNG per panel from the scraped CSV (panel,expr,labels,ts,value)."""
    series: Dict[str, Dict[str, List]] = defaultdict(lambda: defaultdict(list))
    with open(metrics_csv, newline="", encoding="utf-8") as f:
        for row in csv.DictReader(f):
            try:
                ts, val = float(row["ts"]), float(row["value"])
            except (ValueError, KeyError):
                continue
            series[row["panel"]][row["labels"]].append((ts, val))
    made = 0
    for panel, by_label in series.items():
        fig, ax = plt.subplots(figsize=(9, 3.5))
        for labels, points in by_label.items():
            points.sort()
            xs = [p[0] - points[0][0] for p in points]
            ys = [p[1] for p in points]
            ax.plot(xs, ys, label=labels[:60] if labels != "{}" else None)
        ax.set_title(panel)
        ax.set_xlabel("seconds into window")
        if any(l != "{}" for l in by_label):
            ax.legend(fontsize=7)
        fig.tight_layout()
        safe = "".join(c if c.isalnum() else "_" for c in panel)[:60]
        fig.savefig(os.path.join(out_dir, "plots", f"{safe}.png"), dpi=110)
        plt.close(fig)
        made += 1
    return made


# --------------------------------------------------------------------------
# Main
# --------------------------------------------------------------------------


def analyse_iat_distributions(arrivals_ms: List[float], out_dir: str) -> Optional[dict]:
    iat = interarrival_seconds(arrivals_ms)
    if len(iat) < 5:
        print(f"[plot] only {len(iat)} interarrivals; skipping analysis",
              file=sys.stderr)
        return None
    fits = fit_distributions(iat)
    desc = descriptives(iat)
    report = interpret(desc, fits)
    analysis = {"descriptives": desc, "fits": fits, "interpretation": report}
    with open(os.path.join(out_dir, "iat_analysis.json"), "w",
              encoding="utf-8") as f:
        json.dump(analysis, f, indent=2)
    with open(os.path.join(out_dir, "iat_report.txt"), "w",
              encoding="utf-8") as f:
        f.write(report + "\n")
    plot_iat(iat, fits, out_dir)
    return analysis


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--experiment-dir", required=True,
                    help="dir containing run subdirs + metrics.csv")
    ap.add_argument("--calls-log", default=os.path.join(
        os.environ.get("TELEMETRY_LOG_DIR", "logs"), "llm_calls.jsonl"))
    ap.add_argument("--out-dir", default=None)
    args = ap.parse_args(argv)

    out_dir = args.out_dir or args.experiment_dir
    os.makedirs(os.path.join(out_dir, "plots"), exist_ok=True)

    run_dirs = [d for d in glob.glob(os.path.join(args.experiment_dir, "*"))
                if os.path.isdir(d)]
    arrivals = arrivals_from_responses(run_dirs)
    if len(arrivals) < 6:
        arrivals = arrivals_from_calls_log(args.calls_log)
    analysis = analyse_iat_distributions(arrivals, out_dir)
    if analysis:
        print(analysis["interpretation"])

    metrics_csv = os.path.join(args.experiment_dir, "metrics.csv")
    if os.path.isfile(metrics_csv):
        n = plot_metric_rows(metrics_csv, out_dir)
        print(f"[plot] {n} metric panels plotted", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
