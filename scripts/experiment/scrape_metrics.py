#!/usr/bin/env python3
"""Scrape Prometheus over a time window into metrics.csv — dashboard-as-schema.

Rebuild of the reference scraper (reference:
scripts/experiment/scrape_metrics.py:34-219): the set of PromQL expressions
is read out of the Grafana dashboard JSON (every panel target), so whatever
the dashboard shows is exactly what experiments record — one schema, zero
drift. Falls back to a built-in core expression list when the dashboard file
is absent.

Output CSV: one row per (expr, series, timestamp): expr,panel,labels,ts,value
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import sys
import time
import urllib.parse
import urllib.request
from typing import Any, Dict, Iterable, List, Optional, Tuple

DEFAULT_DASHBOARD = os.path.join(
    os.path.dirname(__file__), "..", "..", "infra", "monitoring", "grafana",
    "dashboards", "agentic-traffic.json")

CORE_EXPRS = [
    ("LLM request rate", 'sum(rate(llm_requests_total[30s]))'),
    ("LLM p50 latency", 'histogram_quantile(0.5, sum(rate(llm_request_latency_seconds_bucket[1m])) by (le))'),
    ("LLM p95 latency", 'histogram_quantile(0.95, sum(rate(llm_request_latency_seconds_bucket[1m])) by (le))'),
    ("TTFT p50", 'histogram_quantile(0.5, sum(rate(llm_queue_wait_seconds_bucket[1m])) by (le))'),
    ("Prompt tok/s", 'sum(rate(llm_prompt_tokens_total[1m]))'),
    ("Completion tok/s", 'sum(rate(llm_completion_tokens_total[1m]))'),
    ("Inflight", 'llm_inflight_requests'),
    ("Mean interarrival", '1 / sum(rate(llm_requests_total[30s]))'),
    ("KV cache tokens", 'llm_kv_cache_total_tokens'),
    ("TCP bytes to LLM", 'sum(rate(tcp_bytes_total{dst_service="llm_backend"}[1m]))'),
    ("TCP RTT p95 a->llm", 'histogram_quantile(0.95, sum(rate(tcp_rtt_handshake_seconds_bucket{src_service="agent_a",dst_service="llm_backend"}[5m])) by (le))'),
]


def load_dashboard_panels(path: str) -> List[Tuple[str, str]]:
    """Walk the Grafana dashboard JSON; return (panel_title, expr) pairs."""
    with open(path, encoding="utf-8") as f:
        dash = json.load(f)
    pairs: List[Tuple[str, str]] = []

    def walk(panels: Iterable[Dict[str, Any]]) -> None:
        for p in panels or []:
            title = p.get("title", "?")
            for t in p.get("targets") or []:
                expr = t.get("expr")
                if expr:
                    pairs.append((title, expr))
            walk(p.get("panels"))

    walk(dash.get("panels") or dash.get("dashboard", {}).get("panels"))
    return pairs


def query_range(prom_url: str, expr: str, start: float, end: float,
                step: str) -> List[Dict[str, Any]]:
    params = urllib.parse.urlencode({
        "query": expr, "start": f"{start:.3f}", "end": f"{end:.3f}",
        "step": step})
    url = f"{prom_url}/api/v1/query_range?{params}"
    with urllib.request.urlopen(url, timeout=30) as resp:
        payload = json.loads(resp.read())
    if payload.get("status") != "success":
        raise RuntimeError(f"prometheus error for {expr!r}: {payload}")
    return payload["data"]["result"]


def scrape_to_csv(prom_url: str, pairs: List[Tuple[str, str]], start: float,
                  end: float, step: str, out_path: str) -> int:
    rows = 0
    with open(out_path, "w", newline="", encoding="utf-8") as f:
        writer = csv.writer(f)
        writer.writerow(["panel", "expr", "labels", "ts", "value"])
        for panel, expr in pairs:
            try:
                series = query_range(prom_url, expr, start, end, step)
            except Exception as e:
                print(f"[scrape] skip {expr!r}: {e}", file=sys.stderr)
                continue
            for s in series:
                labels = json.dumps(s.get("metric", {}), sort_keys=True)
                for ts, value in s.get("values", []):
                    writer.writerow([panel, expr, labels, ts, value])
                    rows += 1
    return rows


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--prometheus",
                    default=os.environ.get("PROMETHEUS_URL",
                                           "http://localhost:9090"))
    ap.add_argument("--dashboard", default=DEFAULT_DASHBOARD)
    ap.add_argument("--start", type=float, default=None,
                    help="unix ts (default: now - 15m)")
    ap.add_argument("--end", type=float, default=None)
    ap.add_argument("--step", default="5s")
    ap.add_argument("--out", default="metrics.csv")
    args = ap.parse_args(argv)

    end = args.end or time.time()
    start = args.start or end - 900
    if os.path.isfile(args.dashboard):
        pairs = load_dashboard_panels(args.dashboard)
        print(f"[scrape] {len(pairs)} exprs from dashboard", file=sys.stderr)
    else:
        pairs = CORE_EXPRS
        print("[scrape] dashboard not found, using core exprs", file=sys.stderr)
    rows = scrape_to_csv(args.prometheus.rstrip("/"), pairs, start, end,
                         args.step, args.out)
    print(f"[scrape] wrote {rows} rows -> {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
