#!/usr/bin/env bash
# Long-running aggregated experiment with watchdog (reference:
# scripts/experiment/run_aggregated_experiment.sh): kills stale runs, waits a
# stabilization period, launches run_experiment.sh detached under nohup, and
# installs the cron watchdog that resumes it after crashes.
set -u
SCRIPT_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
ITERATIONS="${1:-5}"
STABILIZE_S="${STABILIZE_S:-300}"
LOG="${LOG:-/tmp/agentic_experiment.log}"

echo "[agg] stopping stale experiment processes"
pkill -f "run_experiment.sh" 2>/dev/null || true

echo "[agg] stabilizing for ${STABILIZE_S}s (let metrics settle)"
sleep "$STABILIZE_S"

echo "[agg] launching run_experiment.sh -n $ITERATIONS (log: $LOG)"
nohup "$SCRIPT_DIR/run_experiment.sh" -n "$ITERATIONS" >> "$LOG" 2>&1 &
EXP_PID=$!
echo "[agg] pid $EXP_PID"

# Install the watchdog cron (every 10 min) unless already present.
WATCHDOG="$SCRIPT_DIR/monitor_experiment.sh"
if command -v crontab >/dev/null 2>&1; then
  ( crontab -l 2>/dev/null | grep -v monitor_experiment.sh
    echo "*/10 * * * * $WATCHDOG >> $LOG 2>&1" ) | crontab -
  echo "[agg] watchdog cron installed"
else
  echo "[agg] crontab unavailable; run $WATCHDOG periodically by hand"
fi
