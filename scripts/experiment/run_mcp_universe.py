#!/usr/bin/env python3
"""Discover and run MCP-Universe benchmark modules against the local backend
through the OpenAI proxy (reference: scripts/experiment/run_mcp_universe.py:41-166).

The benchmark suite itself is an external checkout (env MCP_UNIVERSE_DIR);
this driver injects PYTHONPATH, points the OpenAI SDK at the local proxy,
discovers test modules per domain, and runs them, collecting pass/fail.
Without a checkout it lists what it would run and exits 0 — the testbed
remains self-contained.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List

DOMAINS = ["browser_automation", "financial_analysis", "location_navigation",
           "multi_server", "repository_management", "web_search"]


def discover_benchmarks(universe_dir: str, domains: List[str]) -> List[str]:
    found = []
    for domain in domains:
        base = os.path.join(universe_dir, "tests", domain)
        if not os.path.isdir(base):
            continue
        for name in sorted(os.listdir(base)):
            if name.startswith("test_") and name.endswith(".py"):
                found.append(os.path.join(base, name))
    return found


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--universe-dir",
                    default=os.environ.get("MCP_UNIVERSE_DIR", ""))
    ap.add_argument("--proxy-url",
                    default=os.environ.get("OPENAI_PROXY_URL",
                                           "http://localhost:8400/v1"))
    ap.add_argument("--domains", nargs="*", default=DOMAINS)
    args = ap.parse_args()

    if not args.universe_dir or not os.path.isdir(args.universe_dir):
        print("[mcp-universe] no benchmark checkout (set MCP_UNIVERSE_DIR); "
              f"would run domains: {', '.join(args.domains)}")
        return 0

    modules = discover_benchmarks(args.universe_dir, args.domains)
    if not modules:
        print("[mcp-universe] no test modules discovered", file=sys.stderr)
        return 1

    env = dict(os.environ,
               PYTHONPATH=args.universe_dir + os.pathsep
               + os.environ.get("PYTHONPATH", ""),
               OPENAI_BASE_URL=args.proxy_url,
               OPENAI_API_KEY=os.environ.get("OPENAI_API_KEY", "local"))
    failures = 0
    for mod in modules:
        print(f"[mcp-universe] running {os.path.relpath(mod, args.universe_dir)}")
        proc = subprocess.run([sys.executable, "-m", "pytest", "-x", "-q", mod],
                              env=env)
        if proc.returncode != 0:
            failures += 1
    print(f"[mcp-universe] {len(modules) - failures}/{len(modules)} modules passed")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
