#!/usr/bin/env python3
"""Run the BASELINE.md §3 workload matrix against the real backend, no Docker.

Spawns the full testbed as local processes — LLM backend (TPU), OpenAI
proxy, 5 agent-b replicas, agent-a, mcp-tool-db — wired by the same env
contract the compose files use, then drives the baseline workloads:

    direct      /chat bs=1 sequential greedy (TTFT + per-request tok/s)
    openai      /v1/chat/completions through tools/mcp_universe proxy
    fanout      agent-a `agentic_parallel` -> 5 agent-b in parallel
                (the 5x fan-out pattern BASELINE.md §2 names the target load)
    agentverse  full 4-stage workflow, 1 iteration

Emits one JSON line per scenario and (with --out) a markdown table.

Usage:
    python scripts/experiment/tpu_bench.py --model llama-3.2-1b
    python scripts/experiment/tpu_bench.py --model llama-3.1-8b \
        --quantization int8 --scenarios direct,openai --out docs/BENCHMARKS.md
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BASE_LLM = 18600
BASE_PROXY = 18610
BASE_A = 18620
BASE_B = 18630
BASE_TOOL = 18640


def _http(method: str, url: str, body: dict | None = None, timeout: float = 600.0):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _get_text(url: str) -> str:
    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.read().decode()


class Stack:
    """Local-process testbed; the compose topology without Docker."""

    def __init__(self, args):
        self.args = args
        self.procs: list[subprocess.Popen] = []

    def spawn(self, module: str, env: dict, log_name: str) -> subprocess.Popen:
        full_env = {**os.environ, **{k: str(v) for k, v in env.items()}}
        log = open(f"/tmp/tpu_bench_{log_name}.log", "w")
        p = subprocess.Popen([sys.executable, "-m", module], cwd=REPO,
                             env=full_env, stdout=log, stderr=log)
        self.procs.append(p)
        return p

    def wait_healthy(self, url: str, timeout: float, what: str) -> None:
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            try:
                urllib.request.urlopen(url, timeout=5)
                return
            except Exception:
                time.sleep(2.0)
        raise RuntimeError(f"{what} not healthy after {timeout:.0f}s ({url})")

    def start_llm(self) -> None:
        a = self.args
        env = {
            "LLM_MODEL": a.model, "LLM_PORT": BASE_LLM, "LLM_DTYPE": a.dtype,
            "LLM_MAX_NUM_SEQS": 8, "LLM_MAX_MODEL_LEN": a.max_model_len,
            "LLM_MAX_TOKENS": a.max_tokens, "LLM_TEMPERATURE": "0.0",
        }
        if a.quantization:
            env["LLM_QUANTIZATION"] = a.quantization
        if a.prefix_caching:
            env["LLM_PREFIX_CACHING"] = "1"
        if a.speculation:
            env["LLM_SPECULATION"] = a.speculation
        self.spawn("agentic_traffic_testing_tpu.serving", env, "llm")
        self.wait_healthy(f"http://127.0.0.1:{BASE_LLM}/health",
                          a.llm_start_timeout, "llm-backend")

    def start_agents(self) -> None:
        llm_url = f"http://127.0.0.1:{BASE_LLM}/chat"
        b_urls = []
        for i in range(5):
            port = BASE_B + i
            self.spawn("agentic_traffic_testing_tpu.agents.agent_b",
                       {"AGENT_PORT": port, "AGENT_ID": f"agent_b_{i+1}",
                        "LLM_SERVER_URL": llm_url,
                        "AGENT_B_MAX_TOKENS": self.args.agent_max_tokens,
                        "TELEMETRY_LOG_DIR": "/tmp/tpu_bench_logs"},
                       f"agent_b{i+1}")
            b_urls.append(f"http://127.0.0.1:{port}")
        self.spawn("agentic_traffic_testing_tpu.tools.mcp_tool_db.server",
                   {"TOOL_DB_PORT": BASE_TOOL,
                    "TELEMETRY_LOG_DIR": "/tmp/tpu_bench_logs"}, "tooldb")
        self.spawn("agentic_traffic_testing_tpu.agents.agent_a",
                   {"AGENT_PORT": BASE_A, "LLM_SERVER_URL": llm_url,
                    "AGENT_B_URLS": ",".join(b_urls),
                    "AGENT_A_MAX_TOKENS": self.args.agent_max_tokens,
                    "TOOL_DB_URL": f"http://127.0.0.1:{BASE_TOOL}/query",
                    "TELEMETRY_LOG_DIR": "/tmp/tpu_bench_logs"}, "agent_a")
        for i in range(5):
            self.wait_healthy(f"http://127.0.0.1:{BASE_B+i}/health", 120, f"agent-b-{i+1}")
        self.wait_healthy(f"http://127.0.0.1:{BASE_A}/health", 120, "agent-a")

    def start_proxy(self) -> None:
        self.spawn("agentic_traffic_testing_tpu.tools.mcp_universe.openai_proxy",
                   {"OPENAI_PROXY_PORT": BASE_PROXY,
                    "LLM_SERVER_URL": f"http://127.0.0.1:{BASE_LLM}/chat"},
                   "proxy")
        self.wait_healthy(f"http://127.0.0.1:{BASE_PROXY}/health", 60, "openai-proxy")

    def metric_value(self, name: str) -> float:
        total = 0.0
        for line in _get_text(f"http://127.0.0.1:{BASE_LLM}/metrics").splitlines():
            if line.startswith(name + " ") or (line.startswith(name + "{")):
                total += float(line.rsplit(" ", 1)[1])
        return total

    def shutdown(self) -> None:
        for p in self.procs:
            p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


PROMPT = ("Summarize, in three sentences, why measuring network traffic of "
          "multi-agent LLM systems requires correlating application-level "
          "request identifiers with packet-level flows across layers.")


def bench_direct(stack: Stack, n: int) -> dict:
    lat, ttft, tps = [], [], []
    _http("POST", f"http://127.0.0.1:{BASE_LLM}/chat",
          {"prompt": PROMPT, "max_tokens": 8})  # bucket warmup
    for _ in range(n):
        r = _http("POST", f"http://127.0.0.1:{BASE_LLM}/chat",
                  {"prompt": PROMPT, "max_tokens": stack.args.max_tokens})
        m = r["meta"]
        lat.append(m["latency_ms"] / 1e3)
        ttft.append(m["queue_wait_s"])
        dur = max(1e-6, m["latency_ms"] / 1e3 - m["queue_wait_s"])
        tps.append(m["completion_tokens"] / dur)
    return {
        "scenario": "direct_chat_bs1",
        "requests": n,
        "p50_latency_s": round(statistics.median(lat), 3),
        "p50_ttft_s": round(statistics.median(ttft), 3),
        "decode_tok_s_per_req": round(statistics.median(tps), 1),
    }


def bench_openai(stack: Stack, n: int) -> dict:
    lat = []
    url = f"http://127.0.0.1:{BASE_PROXY}/v1/chat/completions"
    body = {"model": stack.args.model,
            "messages": [{"role": "user", "content": PROMPT}],
            "max_tokens": stack.args.max_tokens}
    _http("POST", url, body)
    for _ in range(n):
        t0 = time.monotonic()
        r = _http("POST", url, body)
        lat.append(time.monotonic() - t0)
        # Structural check only: with random weights greedy decode may emit
        # EOS immediately, which is a legitimately empty completion.
        assert "content" in r["choices"][0]["message"], r
    return {"scenario": "openai_proxy_bs1", "requests": n,
            "p50_latency_s": round(statistics.median(lat), 3)}


def _llm_window(stack: Stack, fn) -> dict:
    tok0 = stack.metric_value("llm_completion_tokens_total")
    req0 = stack.metric_value("llm_requests_total")
    t0 = time.monotonic()
    out = fn()
    dt = time.monotonic() - t0
    toks = stack.metric_value("llm_completion_tokens_total") - tok0
    reqs = stack.metric_value("llm_requests_total") - req0
    out.update({"wall_s": round(dt, 2), "llm_calls": int(reqs),
                "completion_tokens": int(toks),
                "agg_decode_tok_s": round(toks / dt, 1)})
    return out


def bench_fanout(stack: Stack, n: int) -> dict:
    # Untimed warmup task: first hits compile the fan-out's prefill/decode
    # buckets; steady-state is what the baseline compares.
    _http("POST", f"http://127.0.0.1:{BASE_A}/task",
          {"task": PROMPT, "scenario": "agentic_parallel", "agent_count": 5})

    def run():
        lat = []
        for _ in range(n):
            t0 = time.monotonic()
            r = _http("POST", f"http://127.0.0.1:{BASE_A}/task",
                      {"task": PROMPT, "scenario": "agentic_parallel",
                       "agent_count": 5})
            lat.append(time.monotonic() - t0)
            assert "result" in r or "final_output" in r or r, r
        return {"scenario": "agentic_parallel_fanout5", "tasks": n,
                "p50_task_latency_s": round(statistics.median(lat), 2)}
    return _llm_window(stack, run)


def bench_agentverse(stack: Stack) -> dict:
    _http("POST", f"http://127.0.0.1:{BASE_A}/agentverse",
          {"task": PROMPT, "max_iterations": 1, "num_experts": 2,
           "stream": False})  # untimed warmup (bucket compiles)

    def run():
        t0 = time.monotonic()
        r = _http("POST", f"http://127.0.0.1:{BASE_A}/agentverse",
                  {"task": PROMPT, "max_iterations": 1, "num_experts": 2,
                   "stream": False})
        return {"scenario": "agentverse_1iter", "tasks": 1,
                "workflow_latency_s": round(time.monotonic() - t0, 2),
                "success": bool(r.get("success", r.get("final_output")))}
    return _llm_window(stack, run)


def to_markdown(rows: list[dict], args) -> str:
    lines = [
        "## " + (f"{args.model}"
                 + (f" ({args.quantization})" if args.quantization else " (bf16)")
                 + (" + prefix caching" if args.prefix_caching else "")
                 + (f" + {args.speculation} speculation" if args.speculation else "")
                 + " — single TPU v5e chip"),
        "",
        "| scenario | key metrics |",
        "|---|---|",
    ]
    for r in rows:
        kv = ", ".join(f"{k}={v}" for k, v in r.items() if k != "scenario")
        lines.append(f"| {r['scenario']} | {kv} |")
    return "\n".join(lines) + "\n"


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="llama-3.2-1b")
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--quantization", default="")
    ap.add_argument("--prefix-caching", action="store_true")
    ap.add_argument("--speculation", default="",
                    help="'ngram' serves with prompt-lookup speculative decoding")
    ap.add_argument("--max-model-len", type=int, default=2048)
    ap.add_argument("--max-tokens", type=int, default=128)
    ap.add_argument("--agent-max-tokens", type=int, default=128)
    ap.add_argument("--requests", type=int, default=5)
    ap.add_argument("--scenarios", default="direct,openai,fanout,agentverse")
    ap.add_argument("--llm-start-timeout", type=float, default=1800)
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    wanted = set(args.scenarios.split(","))

    stack = Stack(args)
    rows = []
    try:
        stack.start_llm()
        if wanted & {"openai"}:
            stack.start_proxy()
        if wanted & {"fanout", "agentverse"}:
            stack.start_agents()
        if "direct" in wanted:
            rows.append(bench_direct(stack, args.requests))
            print(json.dumps(rows[-1]), flush=True)
        if "openai" in wanted:
            rows.append(bench_openai(stack, args.requests))
            print(json.dumps(rows[-1]), flush=True)
        if "fanout" in wanted:
            rows.append(bench_fanout(stack, max(2, args.requests // 2)))
            print(json.dumps(rows[-1]), flush=True)
        if "agentverse" in wanted:
            rows.append(bench_agentverse(stack))
            print(json.dumps(rows[-1]), flush=True)
    finally:
        stack.shutdown()

    if args.out:
        md = to_markdown(rows, args)
        mode = "a" if os.path.exists(args.out) else "w"
        with open(args.out, mode) as f:
            if mode == "w":
                f.write("# Measured benchmarks (tpu_bench.py)\n\n")
            f.write(md + "\n")
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
