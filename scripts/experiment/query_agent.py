#!/usr/bin/env python3
"""One-shot CLI client for Agent A / Agent B (reference:
scripts/experiment/query_agent.py).

Examples:
    query_agent.py --task "summarize X" --scenario agentic_parallel
    query_agent.py --agent b --subtask "add 2+2"
    query_agent.py --task "plan it" --agentverse
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request


def post(url: str, body: dict, timeout: float) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--agent", choices=["a", "b"], default="a")
    ap.add_argument("--task", help="task text (agent a)")
    ap.add_argument("--subtask", help="subtask text (agent b)")
    ap.add_argument("--scenario", default="agentic_simple")
    ap.add_argument("--agentverse", action="store_true")
    ap.add_argument("--agent-count", type=int, default=None)
    ap.add_argument("--max-tokens", type=int, default=None)
    ap.add_argument("--timeout", type=float, default=600.0)
    args = ap.parse_args()

    if args.agent == "b":
        url = os.environ.get("AGENT_B_URLS",
                             "http://localhost:8201").split(",")[0].rstrip("/")
        if not args.subtask:
            ap.error("--subtask required with --agent b")
        out = post(f"{url}/subtask", {"subtask": args.subtask}, args.timeout)
    else:
        url = os.environ.get("AGENT_A_URL", "http://localhost:8101").rstrip("/")
        if not args.task:
            ap.error("--task required with --agent a")
        if args.agentverse:
            out = post(f"{url}/agentverse", {"task": args.task}, args.timeout)
        else:
            body = {"task": args.task, "scenario": args.scenario}
            if args.agent_count:
                body["agent_count"] = args.agent_count
            if args.max_tokens:
                body["max_tokens"] = args.max_tokens
            out = post(f"{url}/task", body, args.timeout)
    json.dump(out, sys.stdout, indent=2, ensure_ascii=False)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
