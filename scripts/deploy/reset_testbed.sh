#!/usr/bin/env bash
# Uninstall + redeploy (reference: scripts/deploy/reset_testbed.sh).
set -eu
SCRIPT_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
bash "$SCRIPT_DIR/uninstall_testbed.sh" -y
bash "$SCRIPT_DIR/deploy.sh" "${1:-}"
