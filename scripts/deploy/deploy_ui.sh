#!/usr/bin/env bash
# UI deploy helper (reference: scripts/deploy/deploy_ui.sh). Builds and
# (re)starts only the static UI container against an already-running testbed —
# the fast path when iterating on ui/ without touching agents or the backend.
set -euo pipefail

SCRIPT_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
REPO_ROOT="$(cd "$SCRIPT_DIR/../.." && pwd)"
INFRA="$REPO_ROOT/infra"

if [ -f "$INFRA/.env" ]; then set -a; . "$INFRA/.env"; set +a; fi
MODE="${1:-${DEPLOYMENT_MODE:-distributed}}"

case "$MODE" in
  single)      COMPOSE="$INFRA/docker-compose.yml" ;;
  distributed) COMPOSE="$INFRA/docker-compose.distributed.yml" ;;
  *) echo "unknown mode: $MODE (single|distributed)" >&2; exit 2 ;;
esac

docker compose -f "$COMPOSE" up --build -d ui
echo "[deploy] UI at http://localhost:${UI_PORT:-3000} (chat: /chat/, agentverse: /agentverse/)"
