#!/usr/bin/env bash
# Full removal: containers, volumes, networks, logs (reference:
# scripts/deploy/uninstall_testbed.sh). Asks first unless -y.
set -u
SCRIPT_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
REPO_ROOT="$(cd "$SCRIPT_DIR/../.." && pwd)"
INFRA="$REPO_ROOT/infra"

if [ "${1:-}" != "-y" ]; then
  printf "Remove ALL testbed containers, volumes and logs? [y/N] "
  read -r ans
  [ "$ans" = "y" ] || { echo "aborted"; exit 1; }
fi

pkill -f tcp_metrics_collector.py 2>/dev/null || true
for f in docker-compose.monitoring.yml docker-compose.monitoring.distributed.yml \
         docker-compose.distributed.yml docker-compose.yml; do
  [ -f "$INFRA/$f" ] && docker compose -f "$INFRA/$f" down -v --rmi local 2>/dev/null
done
rm -rf "$REPO_ROOT/logs" "$REPO_ROOT/data/experiments"
echo "[uninstall] removed containers, volumes, logs"
