#!/usr/bin/env bash
# Bring up the 4-VM Vagrant topology and deploy per-node services over SSH
# (reference: scripts/deploy/deploy_vms.sh + deploy.sh:120-186).
set -u
SCRIPT_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
INFRA="$(cd "$SCRIPT_DIR/../../infra" && pwd)"

command -v vagrant >/dev/null || { echo "vagrant required" >&2; exit 2; }
cd "$INFRA"
vagrant up

# Per-node role deployment: each VM runs the single-mode compose restricted
# to its role's services.
declare -A ROLES=(
  [agent-a-node]="agent-a ui"
  [agent-b-node]="agent-b"
  [llm-node]="llm-backend-tpu"
  [tools-node]="mcp-tool-db"
)
for node in "${!ROLES[@]}"; do
  echo "[vms] deploying ${ROLES[$node]} on $node"
  vagrant ssh "$node" -c \
    "cd /vagrant && docker compose -f docker-compose.yml up -d ${ROLES[$node]}" \
    || echo "[vms] $node deploy failed" >&2
done
echo "[vms] multi-vm deployment complete"
