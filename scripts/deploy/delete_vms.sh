#!/usr/bin/env bash
# Destroy the Vagrant VMs (reference: scripts/deploy/delete_vms.sh).
set -u
INFRA="$(cd "$(dirname "${BASH_SOURCE[0]}")/../../infra" && pwd)"
command -v vagrant >/dev/null || { echo "vagrant required" >&2; exit 2; }
cd "$INFRA" && vagrant destroy -f
