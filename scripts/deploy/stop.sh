#!/usr/bin/env bash
# Stop all testbed containers, keep volumes/images (reference:
# scripts/deploy/stop.sh).
set -u
SCRIPT_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
INFRA="$(cd "$SCRIPT_DIR/../../infra" && pwd)"

pkill -f tcp_metrics_collector.py 2>/dev/null || true
for f in docker-compose.monitoring.yml docker-compose.monitoring.distributed.yml \
         docker-compose.distributed.yml docker-compose.yml; do
  [ -f "$INFRA/$f" ] && docker compose -f "$INFRA/$f" down 2>/dev/null
done
echo "[stop] testbed stopped (volumes preserved)"
