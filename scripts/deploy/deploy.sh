#!/usr/bin/env bash
# Testbed deployment dispatcher (reference: scripts/deploy/deploy.sh:20-354).
#
# Usage: deploy.sh [single|distributed|multi-vm]   (default: $DEPLOYMENT_MODE)
set -u

SCRIPT_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
REPO_ROOT="$(cd "$SCRIPT_DIR/../.." && pwd)"
INFRA="$REPO_ROOT/infra"

# Load .env (compose also reads it; scripts need the URLs too).
if [ -f "$INFRA/.env" ]; then
  set -a; . "$INFRA/.env"; set +a
fi
MODE="${1:-${DEPLOYMENT_MODE:-distributed}}"
ENABLE_MONITORING="${ENABLE_MONITORING:-1}"
ENABLE_NETWORK_EMULATION="${ENABLE_NETWORK_EMULATION:-0}"

command -v docker >/dev/null || { echo "docker required" >&2; exit 2; }

wait_for_llm() {
  local url="${LLM_HEALTH_URL:-http://localhost:8000/health}"
  echo "[deploy] waiting for LLM backend at $url (first jit compile is slow)"
  for _ in $(seq 1 120); do
    if curl -fsS -m 5 "$url" >/dev/null 2>&1; then
      echo "[deploy] LLM backend healthy"
      return 0
    fi
    sleep 5
  done
  echo "[deploy] LLM backend did not become healthy" >&2
  return 1
}

start_monitoring() {
  echo "[deploy] starting monitoring stack"
  local mon="docker-compose.monitoring.yml"
  [ "$MODE" = "distributed" ] && mon="docker-compose.monitoring.distributed.yml"
  docker compose -f "$INFRA/$mon" up -d
  # Host-side TCP collector over the inter-agent bridge.
  nohup bash "$SCRIPT_DIR/../monitoring/run_tcpdump.sh" \
      > /tmp/tcp_collector.log 2>&1 &
  echo "[deploy] tcp collector started (log: /tmp/tcp_collector.log)"
}

case "$MODE" in
  single)
    docker compose -f "$INFRA/docker-compose.yml" up --build -d
    ;;
  distributed)
    docker compose -f "$INFRA/docker-compose.distributed.yml" up --build -d
    ;;
  multi-vm)
    bash "$SCRIPT_DIR/deploy_vms.sh"
    exit $?
    ;;
  *)
    echo "unknown mode: $MODE (single|distributed|multi-vm)" >&2
    exit 2
    ;;
esac

[ "$ENABLE_MONITORING" = "1" ] && start_monitoring

bash "$SCRIPT_DIR/../fetch_endpoints.sh" || true
wait_for_llm || true
python3 "$SCRIPT_DIR/../monitoring/health_check.py" || true

if [ "$ENABLE_NETWORK_EMULATION" = "1" ]; then
  bash "$SCRIPT_DIR/../traffic/apply_network_emulation.sh" apply \
    "${NETEM_DELAY_MS:-10}" "${NETEM_JITTER_MS:-2}" "${NETEM_LOSS_PCT:-0}"
fi

echo "[deploy] done (mode=$MODE)"
