#!/usr/bin/env python3
"""tcpdump -> Prometheus TCP metrics exporter (L2 capture plane).

Rebuild of the reference collector (reference:
scripts/monitoring/tcp_metrics_collector.py:43-568): parse `tcpdump -tt -n`
lines from a live subprocess or stdin, track per-flow state, pair SYN with
SYN-ACK for handshake RTT, and serve hand-rolled Prometheus text on :9100.

Metric families (names unchanged so the Grafana dashboard and scraper work
against either testbed):

    tcp_packets_total{src_service,dst_service}
    tcp_bytes_total{src_service,dst_service}
    tcp_syn_total{src_service,dst_service}
    tcp_rtt_handshake_seconds{src_service,dst_service} (histogram)
    tcp_active_flows
    tcp_flow_duration_seconds (histogram, on flow expiry)

Service names come from an IP->service map (env-overridable, defaults match
the compose IP plan in infra/.env.example). Unknown IPs map to "external".

Usage:
    tcp_metrics_collector.py --interface br-inter_agent   # spawns tcpdump
    sudo tcpdump -tt -n -i any tcp | tcp_metrics_collector.py --read-stdin
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Iterable, List, Optional, Tuple

# ---------------------------------------------------------------------------
# IP -> service mapping (env-overridable; defaults = compose static IP plan)
# ---------------------------------------------------------------------------

DEFAULT_IP_MAP = {
    "172.23.0.10": "agent_a",
    "172.23.0.11": "agent_b",
    "172.23.0.12": "agent_b_2",
    "172.23.0.13": "agent_b_3",
    "172.23.0.14": "agent_b_4",
    "172.23.0.15": "agent_b_5",
    "172.23.0.20": "llm_backend",
    "172.23.0.30": "mcp_tool_db",
    "172.23.0.40": "ui",
}


def load_ip_map() -> Dict[str, str]:
    raw = os.environ.get("TCP_COLLECTOR_IP_MAP")
    if raw:
        try:
            return {str(k): str(v) for k, v in json.loads(raw).items()}
        except json.JSONDecodeError:
            print(f"[tcp-collector] bad TCP_COLLECTOR_IP_MAP, using defaults",
                  file=sys.stderr)
    return dict(DEFAULT_IP_MAP)


# ---------------------------------------------------------------------------
# tcpdump line parsing
# ---------------------------------------------------------------------------

# `tcpdump -tt -n`:  1690000000.123456 IP 172.23.0.10.52344 > 172.23.0.20.8000:
#                    Flags [S], seq ..., length 0
_PACKET_RE = re.compile(
    r"^(?P<ts>\d+\.\d+)\s+IP6?\s+"
    r"(?P<src>[\da-fA-F.:]+)\.(?P<sport>\d+)\s+>\s+"
    r"(?P<dst>[\da-fA-F.:]+)\.(?P<dport>\d+):\s+"
    r"Flags\s+\[(?P<flags>[^\]]*)\]"
    r"(?:.*?\blength\s+(?P<length>\d+))?"
)


@dataclass
class Packet:
    ts: float
    src: str
    sport: int
    dst: str
    dport: int
    flags: str
    length: int


def parse_line(line: str) -> Optional[Packet]:
    m = _PACKET_RE.match(line)
    if not m:
        return None
    return Packet(
        ts=float(m.group("ts")),
        src=m.group("src"), sport=int(m.group("sport")),
        dst=m.group("dst"), dport=int(m.group("dport")),
        flags=m.group("flags"),
        length=int(m.group("length") or 0),
    )


# ---------------------------------------------------------------------------
# Flow tracking + metrics
# ---------------------------------------------------------------------------

RTT_BUCKETS = [0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
               0.25, 0.5, 1.0, 2.5]
DURATION_BUCKETS = [0.01, 0.05, 0.1, 0.5, 1, 5, 15, 30, 60, 120, 300]
FLOW_IDLE_TIMEOUT_S = 120.0


@dataclass
class FlowState:
    first_ts: float
    last_ts: float
    packets: int = 0
    bytes: int = 0
    syn_ts: Optional[float] = None   # pending SYN awaiting SYN-ACK


class Histogram:
    """Minimal fixed-bucket histogram (hand-rolled text rendering, like the
    reference's — no prometheus_client dependency for the host collector)."""

    def __init__(self, buckets: List[float]) -> None:
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.total = 0.0
        self.n = 0

    def observe(self, v: float) -> None:
        self.n += 1
        self.total += v
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def render(self, name: str, labels: str) -> Iterable[str]:
        cum = 0
        for b, c in zip(self.buckets, self.counts):
            cum += c
            yield f'{name}_bucket{{{labels},le="{b}"}} {cum}'
        cum += self.counts[-1]
        yield f'{name}_bucket{{{labels},le="+Inf"}} {cum}'
        yield f'{name}_sum{{{labels}}} {self.total:.6f}'
        yield f'{name}_count{{{labels}}} {cum}'


class TCPMetrics:
    """All collector state; one lock shared by the packet thread and the
    /metrics renderer (reference keeps the same split — :138, 224, 312)."""

    def __init__(self, ip_map: Dict[str, str]) -> None:
        self.ip_map = ip_map
        self.lock = threading.Lock()
        self.packets: Dict[Tuple[str, str], int] = {}
        self.bytes: Dict[Tuple[str, str], int] = {}
        self.syns: Dict[Tuple[str, str], int] = {}
        self.rtt: Dict[Tuple[str, str], Histogram] = {}
        self.flow_duration = Histogram(DURATION_BUCKETS)
        self.flows: Dict[Tuple[str, int, str, int], FlowState] = {}
        self.parse_errors = 0
        self.started = time.time()

    def service(self, ip: str) -> str:
        return self.ip_map.get(ip, "external")

    # ------------------------------------------------------------ ingest
    def process_packet(self, pkt: Packet) -> None:
        src_svc, dst_svc = self.service(pkt.src), self.service(pkt.dst)
        edge = (src_svc, dst_svc)
        fkey = (pkt.src, pkt.sport, pkt.dst, pkt.dport)
        rkey = (pkt.dst, pkt.dport, pkt.src, pkt.sport)
        is_syn = "S" in pkt.flags and "." not in pkt.flags  # SYN, not SYN-ACK
        is_synack = "S" in pkt.flags and "." in pkt.flags

        with self.lock:
            self.packets[edge] = self.packets.get(edge, 0) + 1
            self.bytes[edge] = self.bytes.get(edge, 0) + pkt.length
            flow = self.flows.get(fkey)
            if flow is None:
                flow = self.flows[fkey] = FlowState(first_ts=pkt.ts,
                                                    last_ts=pkt.ts)
            flow.packets += 1
            flow.bytes += pkt.length
            flow.last_ts = pkt.ts

            if is_syn:
                self.syns[edge] = self.syns.get(edge, 0) + 1
                flow.syn_ts = pkt.ts
            elif is_synack:
                # RTT = SYN-ACK time minus the reverse flow's pending SYN.
                rev = self.flows.get(rkey)
                if rev is not None and rev.syn_ts is not None:
                    rtt = pkt.ts - rev.syn_ts
                    rev.syn_ts = None
                    if 0 <= rtt < 10:
                        redge = (self.service(pkt.dst), self.service(pkt.src))
                        hist = self.rtt.get(redge)
                        if hist is None:
                            hist = self.rtt[redge] = Histogram(RTT_BUCKETS)
                        hist.observe(rtt)

    def expire_idle_flows(self, now: Optional[float] = None) -> int:
        now = now or time.time()
        expired = 0
        with self.lock:
            for key, flow in list(self.flows.items()):
                if now - flow.last_ts > FLOW_IDLE_TIMEOUT_S:
                    self.flow_duration.observe(flow.last_ts - flow.first_ts)
                    del self.flows[key]
                    expired += 1
        return expired

    # ------------------------------------------------------------ render
    def render(self) -> str:
        out: List[str] = []
        with self.lock:
            out.append("# TYPE tcp_packets_total counter")
            for (s, d), v in sorted(self.packets.items()):
                out.append(f'tcp_packets_total{{src_service="{s}",dst_service="{d}"}} {v}')
            out.append("# TYPE tcp_bytes_total counter")
            for (s, d), v in sorted(self.bytes.items()):
                out.append(f'tcp_bytes_total{{src_service="{s}",dst_service="{d}"}} {v}')
            out.append("# TYPE tcp_syn_total counter")
            for (s, d), v in sorted(self.syns.items()):
                out.append(f'tcp_syn_total{{src_service="{s}",dst_service="{d}"}} {v}')
            out.append("# TYPE tcp_rtt_handshake_seconds histogram")
            for (s, d), hist in sorted(self.rtt.items()):
                out.extend(hist.render(
                    "tcp_rtt_handshake_seconds",
                    f'src_service="{s}",dst_service="{d}"'))
            out.append("# TYPE tcp_flow_duration_seconds histogram")
            out.extend(self.flow_duration.render("tcp_flow_duration_seconds",
                                                 'scope="all"'))
            out.append("# TYPE tcp_active_flows gauge")
            out.append(f"tcp_active_flows {len(self.flows)}")
            out.append("# TYPE tcp_collector_parse_errors_total counter")
            out.append(f"tcp_collector_parse_errors_total {self.parse_errors}")
            out.append("# TYPE tcp_collector_uptime_seconds gauge")
            out.append(f"tcp_collector_uptime_seconds {time.time() - self.started:.1f}")
        return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# Ingest loops + HTTP server
# ---------------------------------------------------------------------------


def reader_loop(metrics: TCPMetrics, stream) -> None:
    for line in stream:
        if isinstance(line, bytes):
            line = line.decode(errors="replace")
        pkt = parse_line(line)
        if pkt is not None:
            metrics.process_packet(pkt)
        elif line.strip() and "listening on" not in line:
            metrics.parse_errors += 1


def expiry_loop(metrics: TCPMetrics, interval_s: float = 10.0) -> None:
    """Dedicated timer: flows must keep expiring (and flushing into the
    duration histogram) after capture goes quiet, when the reader loop is
    blocked on the pipe."""
    while True:
        time.sleep(interval_s)
        metrics.expire_idle_flows()


def spawn_tcpdump(interface: str) -> subprocess.Popen:
    cmd = ["tcpdump", "-tt", "-n", "-l", "-i", interface, "tcp"]
    return subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL)


class MetricsHandler(BaseHTTPRequestHandler):
    metrics: TCPMetrics = None  # injected

    def do_GET(self):  # noqa: N802
        if self.path not in ("/metrics", "/"):
            self.send_response(404)
            self.end_headers()
            return
        body = self.metrics.render().encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # quiet
        pass


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--port", type=int,
                    default=int(os.environ.get("TCP_COLLECTOR_PORT", "9100")))
    ap.add_argument("--interface", default=None,
                    help="spawn tcpdump on this interface")
    ap.add_argument("--read-stdin", action="store_true",
                    help="parse tcpdump output piped to stdin")
    args = ap.parse_args(argv)

    metrics = TCPMetrics(load_ip_map())
    MetricsHandler.metrics = metrics

    if args.read_stdin:
        stream = sys.stdin
        proc = None
    elif args.interface:
        proc = spawn_tcpdump(args.interface)
        stream = proc.stdout
    else:
        ap.error("one of --interface or --read-stdin is required")
        return 2

    threading.Thread(target=reader_loop, args=(metrics, stream),
                     daemon=True).start()
    threading.Thread(target=expiry_loop, args=(metrics,), daemon=True).start()

    server = ThreadingHTTPServer(("0.0.0.0", args.port), MetricsHandler)
    print(f"[tcp-collector] serving /metrics on :{args.port}", file=sys.stderr)

    def shutdown(*_):
        # shutdown() must come from another thread: the handler runs on the
        # main thread, which serve_forever() owns — calling it here deadlocks.
        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, shutdown)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if proc is not None:
            proc.terminate()
    return 0


if __name__ == "__main__":
    sys.exit(main())
