#!/usr/bin/env python3
"""End-to-end testbed health check.

Rebuild of the reference checker (reference:
scripts/monitoring/health_check.py:222-491): probes every layer and — the
part that matters — exercises the agent -> LLM critical path with a real
task, classifying failures (connection refused vs DNS vs 502 vs timeout) so
an operator can tell *which* hop is broken.

Checks, in order:
    1. LLM backend /health + a real POST /chat round trip
    2. Agent A /health, Agent B replicas /health
    3. Critical path: POST /task (agentic_simple) through Agent A to the LLM
    4. Tool DB /query determinism
    5. Observability: Prometheus targets, TCP collector, mapping exporter

Exit code 0 = all required checks green; 1 otherwise. `--json` for machines.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple


def env_url(name: str, default: str) -> str:
    return os.environ.get(name, default).rstrip("/")


def classify_error(e: Exception) -> str:
    if isinstance(e, urllib.error.HTTPError):
        return f"http_{e.code}"
    if isinstance(e, urllib.error.URLError):
        reason = e.reason
        if isinstance(reason, socket.gaierror):
            return "dns_failure"
        if isinstance(reason, ConnectionRefusedError):
            return "connection_refused"
        if isinstance(reason, socket.timeout) or isinstance(reason, TimeoutError):
            return "timeout"
        return f"unreachable:{type(reason).__name__}"
    if isinstance(e, socket.timeout):
        return "timeout"
    return f"{type(e).__name__}"


def http_json(url: str, body: Optional[dict] = None, timeout: float = 10.0,
              headers: Optional[dict] = None) -> Tuple[int, Any]:
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read() or b"{}")


class Check:
    def __init__(self, name: str, required: bool = True) -> None:
        self.name = name
        self.required = required
        self.ok = False
        self.detail: Dict[str, Any] = {}

    def record(self, ok: bool, **detail: Any) -> None:
        self.ok = ok
        self.detail = detail

    def row(self) -> Dict[str, Any]:
        return {"check": self.name, "ok": self.ok,
                "required": self.required, **self.detail}


def check_llm(checks: List[Check], llm_url: str, timeout: float) -> None:
    c = Check("llm.health")
    checks.append(c)
    try:
        status, body = http_json(f"{llm_url}/health", timeout=timeout)
        c.record(status == 200, status=status, body=body)
    except Exception as e:
        c.record(False, error=classify_error(e))

    c = Check("llm.chat_roundtrip")
    checks.append(c)
    try:
        t0 = time.monotonic()
        status, body = http_json(
            f"{llm_url}/chat",
            {"prompt": "health probe", "max_tokens": 4},
            timeout=max(timeout, 60.0))
        meta = body.get("meta", {})
        c.record(status == 200 and "output" in body,
                 status=status, latency_ms=round((time.monotonic() - t0) * 1e3, 1),
                 completion_tokens=meta.get("completion_tokens"))
    except Exception as e:
        c.record(False, error=classify_error(e))


def discover_agent_endpoints() -> Dict[str, str]:
    """Agent URLs from env (compose injects them), reference-compatible names."""
    eps = {"agent_a": env_url("AGENT_A_URL", "http://localhost:8101")}
    for i, url in enumerate(os.environ.get(
            "AGENT_B_URLS", "http://localhost:8201").split(",")):
        url = url.strip().rstrip("/")
        if url:
            eps[f"agent_b_{i + 1}" if i else "agent_b"] = url
    return eps


def check_agents(checks: List[Check], agents: Dict[str, str],
                 timeout: float) -> None:
    for name, url in agents.items():
        c = Check(f"{name}.health")
        checks.append(c)
        try:
            status, body = http_json(f"{url}/health", timeout=timeout)
            c.record(status == 200, status=status,
                     agent_id=body.get("agent_id"))
        except Exception as e:
            c.record(False, error=classify_error(e))


def check_agent_to_llm_connectivity(checks: List[Check], agent_a_url: str,
                                    timeout: float) -> None:
    """The critical path: a real scenario through Agent A down to the LLM."""
    c = Check("critical_path.agent_a_to_llm")
    checks.append(c)
    try:
        t0 = time.monotonic()
        status, body = http_json(
            f"{agent_a_url}/task",
            {"task": "reply with one word", "scenario": "agentic_simple",
             "max_tokens": 4},
            timeout=max(timeout, 120.0))
        steps = (body.get("detail") or {}).get("steps") or []
        step_err = next((s.get("error") for s in steps if s.get("error")), None)
        c.record(status == 200 and not step_err, status=status,
                 latency_ms=round((time.monotonic() - t0) * 1e3, 1),
                 step_error=step_err,
                 tokens=(body.get("aggregates") or {}).get("total_tokens"))
    except Exception as e:
        c.record(False, error=classify_error(e))


def check_tool_db(checks: List[Check], url: str, timeout: float) -> None:
    c = Check("tool_db.query", required=False)
    checks.append(c)
    try:
        _, one = http_json(f"{url}/query", {"query": "probe"}, timeout=timeout)
        _, two = http_json(f"{url}/query", {"query": "probe"}, timeout=timeout)
        c.record(one.get("result") == two.get("result"),
                 deterministic=one.get("result") == two.get("result"))
    except Exception as e:
        c.record(False, error=classify_error(e))


def check_observability(checks: List[Check], timeout: float) -> None:
    probes = {
        "prometheus": env_url("PROMETHEUS_URL", "http://localhost:9090")
        + "/-/ready",
        "tcp_collector": env_url("TCP_COLLECTOR_URL", "http://localhost:9100")
        + "/metrics",
        "docker_mapping": env_url("DOCKER_MAPPING_URL", "http://localhost:9101")
        + "/metrics",
    }
    for name, url in probes.items():
        c = Check(f"observability.{name}", required=False)
        checks.append(c)
        try:
            req = urllib.request.Request(url)
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                c.record(resp.status == 200, status=resp.status)
        except Exception as e:
            c.record(False, error=classify_error(e))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--timeout", type=float, default=10.0)
    ap.add_argument("--skip-observability", action="store_true")
    args = ap.parse_args(argv)

    llm_url = env_url("LLM_SERVER_URL", "http://localhost:8000")
    # LLM_SERVER_URL conventionally includes /chat; strip for /health.
    if llm_url.endswith("/chat"):
        llm_url = llm_url[: -len("/chat")]
    agents = discover_agent_endpoints()

    checks: List[Check] = []
    check_llm(checks, llm_url, args.timeout)
    check_agents(checks, agents, args.timeout)
    check_agent_to_llm_connectivity(checks, agents["agent_a"], args.timeout)
    check_tool_db(checks,
                  env_url("TOOL_DB_URL", "http://localhost:8301"), args.timeout)
    if not args.skip_observability:
        check_observability(checks, args.timeout)

    required_ok = all(c.ok for c in checks if c.required)
    if args.json:
        print(json.dumps({"ok": required_ok,
                          "checks": [c.row() for c in checks]}, indent=2))
    else:
        for c in checks:
            mark = "PASS" if c.ok else ("FAIL" if c.required else "warn")
            detail = " ".join(f"{k}={v}" for k, v in c.detail.items())
            print(f"[{mark:4s}] {c.name:35s} {detail}")
        print(f"\noverall: {'HEALTHY' if required_ok else 'UNHEALTHY'}")
    return 0 if required_ok else 1


if __name__ == "__main__":
    sys.exit(main())
