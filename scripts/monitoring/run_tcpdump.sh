#!/usr/bin/env bash
# Host-side capture loop: pipe tcpdump into the TCP metrics collector
# (reference: scripts/monitoring/run_tcpdump.sh:1-54). Kills any stale :9100
# listener first so redeploys don't stack collectors.
set -u
SCRIPT_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
PORT="${TCP_COLLECTOR_PORT:-9100}"
IFACE="${TCP_CAPTURE_IFACE:-any}"

# Kill a stale collector holding the port.
if command -v fuser >/dev/null 2>&1; then
  fuser -k "${PORT}/tcp" 2>/dev/null || true
else
  pkill -f "tcp_metrics_collector.py" 2>/dev/null || true
fi
sleep 1

SUDO=""
[ "$(id -u)" != "0" ] && command -v sudo >/dev/null && SUDO="sudo"

echo "[run_tcpdump] capturing on $IFACE -> collector :$PORT"
exec $SUDO tcpdump -tt -n -l -i "$IFACE" tcp 2>/dev/null \
  | python3 "$SCRIPT_DIR/tcp_metrics_collector.py" --read-stdin --port "$PORT"
