#!/usr/bin/env python3
"""Docker network/container/IP mapping -> Prometheus gauges (:9101).

Rebuild of the reference exporter (reference:
scripts/monitoring/docker_mapping_exporter.py:28-193). Talks to the Docker
Engine API over the unix socket with the standard library only, and exports
three always-1 gauge families whose *labels* carry the mapping; dashboards
join them onto tcp_*/container_* series with PromQL `group_left`:

    docker_network_mapping{network,subnet,driver} 1
    docker_container_mapping{container,image,status,network} 1
    docker_ip_mapping{ip,container,network} 1

Mappings are cached for 10 s to keep /metrics cheap under 2 s scrapes.
"""

from __future__ import annotations

import http.client
import json
import os
import socket
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List

DOCKER_SOCKET = os.environ.get("DOCKER_SOCKET", "/var/run/docker.sock")
CACHE_TTL_S = 10.0


class DockerSocketConnection(http.client.HTTPConnection):
    """HTTP over the Docker unix socket (no external deps)."""

    def __init__(self, path: str = DOCKER_SOCKET) -> None:
        super().__init__("localhost")
        self.unix_path = path

    def connect(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(5.0)
        sock.connect(self.unix_path)
        self.sock = sock


def docker_get(path: str) -> Any:
    conn = DockerSocketConnection()
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        if resp.status != 200:
            raise RuntimeError(f"docker api {path}: http {resp.status}")
        return json.loads(resp.read())
    finally:
        conn.close()


def get_docker_mappings() -> Dict[str, List[Dict[str, str]]]:
    """One pass over /networks and /containers/json -> three label sets."""
    networks = []
    ips = []
    containers = []

    for net in docker_get("/networks"):
        subnets = ",".join(c.get("Subnet", "")
                           for c in (net.get("IPAM") or {}).get("Config") or [])
        networks.append({"network": net.get("Name", "?"),
                         "subnet": subnets,
                         "driver": net.get("Driver", "?")})

    for c in docker_get("/containers/json?all=1"):
        name = (c.get("Names") or ["/?"])[0].lstrip("/")
        nets = (c.get("NetworkSettings") or {}).get("Networks") or {}
        if not nets:
            containers.append({"container": name,
                               "image": c.get("Image", "?"),
                               "status": c.get("State", "?"),
                               "network": ""})
        for net_name, net in nets.items():
            containers.append({"container": name,
                               "image": c.get("Image", "?"),
                               "status": c.get("State", "?"),
                               "network": net_name})
            ip = net.get("IPAddress") or ""
            if ip:
                ips.append({"ip": ip, "container": name,
                            "network": net_name})

    return {"networks": networks, "containers": containers, "ips": ips}


_cache: Dict[str, Any] = {"ts": 0.0, "data": None, "error": None}
_cache_lock = threading.Lock()


def cached_mappings() -> Dict[str, Any]:
    with _cache_lock:
        now = time.time()
        if _cache["data"] is None or now - _cache["ts"] > CACHE_TTL_S:
            try:
                _cache["data"] = get_docker_mappings()
                _cache["error"] = None
            except Exception as e:
                _cache["error"] = f"{type(e).__name__}: {e}"
                _cache["data"] = _cache["data"] or {
                    "networks": [], "containers": [], "ips": []}
            _cache["ts"] = now
        return {"data": _cache["data"], "error": _cache["error"]}


def _labels(d: Dict[str, str]) -> str:
    return ",".join(f'{k}="{str(v).replace(chr(34), "")}"'
                    for k, v in sorted(d.items()))


def generate_metrics() -> str:
    state = cached_mappings()
    data = state["data"]
    lines = [
        "# TYPE docker_network_mapping gauge",
        *[f"docker_network_mapping{{{_labels(n)}}} 1" for n in data["networks"]],
        "# TYPE docker_container_mapping gauge",
        *[f"docker_container_mapping{{{_labels(c)}}} 1" for c in data["containers"]],
        "# TYPE docker_ip_mapping gauge",
        *[f"docker_ip_mapping{{{_labels(i)}}} 1" for i in data["ips"]],
        "# TYPE docker_mapping_up gauge",
        f"docker_mapping_up {0 if state['error'] else 1}",
    ]
    return "\n".join(lines) + "\n"


class Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802
        if self.path not in ("/metrics", "/"):
            self.send_response(404)
            self.end_headers()
            return
        body = generate_metrics().encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):
        pass


def main() -> int:
    port = int(os.environ.get("DOCKER_MAPPING_PORT", "9101"))
    server = ThreadingHTTPServer(("0.0.0.0", port), Handler)
    print(f"[docker-mapping] serving /metrics on :{port}", file=sys.stderr)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
