#!/usr/bin/env bash
# Human-readable docker network / bridge / IP maps (reference:
# scripts/monitoring/print_network_mappings.sh:1-78).
set -u
command -v docker >/dev/null || { echo "docker required" >&2; exit 2; }

echo "== networks (name -> bridge, subnet) =="
docker network ls --format '{{.ID}} {{.Name}}' | while read -r id name; do
  subnet="$(docker network inspect "$id" \
    --format '{{range .IPAM.Config}}{{.Subnet}} {{end}}' 2>/dev/null)"
  echo "  $name -> br-${id:0:12}  $subnet"
done

echo
echo "== containers (name -> network: ip) =="
docker ps --format '{{.Names}}' | while read -r c; do
  docker inspect "$c" --format \
    '{{range $net, $cfg := .NetworkSettings.Networks}}  {{$.Name}} -> {{$net}}: {{$cfg.IPAddress}}{{"\n"}}{{end}}' \
    2>/dev/null
done

echo "== host bridges =="
ls /sys/class/net/ 2>/dev/null | grep '^br-' | sed 's/^/  /' || echo "  (none)"
