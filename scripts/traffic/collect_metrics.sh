#!/usr/bin/env bash
# Launch BCC TCP tracing tools (tcpconnect/tcplife/tcpretrans) on the host,
# one log each (reference: scripts/traffic/collect_metrics.sh). BCC is an
# optional host dependency; missing tools are reported and skipped.
set -u
OUT_DIR="${1:-data/bcc}"
DURATION="${2:-60}"
mkdir -p "$OUT_DIR"

run_tool() {  # $1 tool name
  local tool="$1"
  local path
  path="$(command -v "$tool" || command -v "/usr/share/bcc/tools/$tool" || true)"
  if [ -z "$path" ]; then
    echo "[bcc] $tool not installed, skipping"
    return
  fi
  echo "[bcc] $tool -> $OUT_DIR/$tool.log (${DURATION}s)"
  timeout "$DURATION" sudo "$path" > "$OUT_DIR/$tool.log" 2>&1 &
}

run_tool tcpconnect
run_tool tcplife
run_tool tcpretrans
run_tool tcprtt
wait
echo "[bcc] done -> $OUT_DIR"
