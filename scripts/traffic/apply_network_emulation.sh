#!/usr/bin/env bash
# Apply/remove tc netem delay/jitter/loss inside agent containers.
# Rebuild of the reference netem hook (reference:
# scripts/traffic/apply_network_emulation.sh:48-161). Containers need
# NET_ADMIN (the compose files grant it to agents).
#
# Usage:
#   apply_network_emulation.sh apply   [delay_ms [jitter_ms [loss_pct]]]
#   apply_network_emulation.sh remove
#   apply_network_emulation.sh status
set -u

ACTION="${1:-status}"
DELAY_MS="${2:-${NETEM_DELAY_MS:-10}}"
JITTER_MS="${3:-${NETEM_JITTER_MS:-2}}"
LOSS_PCT="${4:-${NETEM_LOSS_PCT:-0}}"
CONTAINERS="${NETEM_CONTAINERS:-agent-a agent-b agent-b-2 agent-b-3 agent-b-4 agent-b-5}"
DEV="${NETEM_DEV:-eth0}"

command -v docker >/dev/null 2>&1 || { echo "docker required" >&2; exit 2; }

apply_netem() {  # $1 container
  local spec="delay ${DELAY_MS}ms ${JITTER_MS}ms"
  # awk comparison keeps fractional rates (e.g. 0.5) — string/integer tests drop them
  if [ -n "$LOSS_PCT" ] && awk "BEGIN{exit !($LOSS_PCT > 0)}" 2>/dev/null; then
    spec="$spec loss ${LOSS_PCT}%"
  fi
  docker exec "$1" tc qdisc replace dev "$DEV" root netem $spec 2>/dev/null \
    && echo "[netem] $1: $spec" \
    || echo "[netem] $1: FAILED (running? NET_ADMIN? iproute2?)" >&2
}

for c in $CONTAINERS; do
  docker inspect "$c" >/dev/null 2>&1 || continue
  case "$ACTION" in
    apply)  apply_netem "$c" ;;
    remove) docker exec "$c" tc qdisc del dev "$DEV" root 2>/dev/null \
              && echo "[netem] $c: removed" \
              || echo "[netem] $c: nothing to remove" ;;
    status) echo "[netem] $c: $(docker exec "$c" tc qdisc show dev "$DEV" 2>/dev/null || echo unreachable)" ;;
    *) echo "unknown action $ACTION" >&2; exit 2 ;;
  esac
done
