#!/usr/bin/env bash
# Capture inter-agent traffic to a pcap (+ optional docker stats sidecar).
# Rebuild of the reference capture script (reference:
# scripts/traffic/collect_traffic.sh:1-306, find_bridge_interface :104).
#
# Usage: collect_traffic.sh [-d seconds] [-o out_dir] [-i interface] [-s]
set -u

DURATION=60
OUT_DIR="data/traffic"
IFACE=""
DOCKER_STATS=0

while getopts "d:o:i:sh" opt; do
  case "$opt" in
    d) DURATION="$OPTARG" ;;
    o) OUT_DIR="$OPTARG" ;;
    i) IFACE="$OPTARG" ;;
    s) DOCKER_STATS=1 ;;
    h|*) grep '^#' "$0" | sed 's/^# \{0,1\}//'; exit 1 ;;
  esac
done

find_bridge_interface() {
  # The inter-agent compose network is named inter_agent_network; docker
  # names its bridge br-<12-char network id>.
  if command -v docker >/dev/null 2>&1; then
    local net_id
    net_id="$(docker network ls --filter name=inter_agent -q | head -1)"
    if [ -n "$net_id" ]; then
      echo "br-${net_id:0:12}"
      return 0
    fi
  fi
  # Fallback: first br-* interface, else any.
  ls /sys/class/net/ 2>/dev/null | grep '^br-' | head -1 || echo any
}

[ -n "$IFACE" ] || IFACE="$(find_bridge_interface)"
mkdir -p "$OUT_DIR"
STAMP="$(date +%Y%m%d_%H%M%S)"
PCAP="$OUT_DIR/capture_${STAMP}.pcap"

echo "[capture] interface=$IFACE duration=${DURATION}s -> $PCAP"
timeout "$DURATION" tcpdump -i "$IFACE" -w "$PCAP" tcp 2>/dev/null &
TCPDUMP_PID=$!

if [ "$DOCKER_STATS" = "1" ] && command -v docker >/dev/null 2>&1; then
  STATS="$OUT_DIR/docker_stats_${STAMP}.jsonl"
  echo "[capture] docker stats -> $STATS"
  ( end=$((SECONDS + DURATION))
    while [ $SECONDS -lt $end ]; do
      docker stats --no-stream --format '{{json .}}' 2>/dev/null
      sleep 2
    done ) > "$STATS" &
fi

wait "$TCPDUMP_PID" 2>/dev/null || true

SIZE="$(stat -c%s "$PCAP" 2>/dev/null || echo 0)"
echo "[capture] done ($SIZE bytes)"
echo "[capture] analyze with: python3 scripts/traffic/analyze_traffic.py $PCAP"
