#!/usr/bin/env python3
"""Offline pcap analysis: per-flow stats, time series, telemetry join.

Rebuild of the reference analyzer (reference:
scripts/traffic/analyze_traffic.py:67-421), which used scapy; this version
parses the classic libpcap format first-party (struct unpacking of the
global header, per-record headers, and Ethernet/IPv4/TCP headers) — no
capture dependencies, reads what `tcpdump -w` writes.

Outputs: per-flow CSV, per-second connections/bytes CSV, and an optional
join against telemetry JSONL event windows.
"""

from __future__ import annotations

import argparse
import csv
import glob
import json
import os
import struct
import sys
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

PCAP_MAGIC_LE = 0xA1B2C3D4
PCAP_MAGIC_LE_NS = 0xA1B23C4D
LINKTYPE_ETHERNET = 1
LINKTYPE_LINUX_SLL = 113
LINKTYPE_RAW = 101


@dataclass
class PcapPacket:
    ts: float
    src: str
    dst: str
    sport: int
    dport: int
    flags: int
    payload_len: int
    wire_len: int

    @property
    def is_syn(self) -> bool:
        return bool(self.flags & 0x02) and not (self.flags & 0x10)

    @property
    def is_fin_or_rst(self) -> bool:
        return bool(self.flags & 0x05)


def read_pcap(path: str) -> Iterator[PcapPacket]:
    """Yield TCP packets from a classic-format pcap file."""
    with open(path, "rb") as f:
        header = f.read(24)
        if len(header) < 24:
            return
        magic = struct.unpack("<I", header[:4])[0]
        if magic in (PCAP_MAGIC_LE, PCAP_MAGIC_LE_NS):
            endian, ns = "<", magic == PCAP_MAGIC_LE_NS
        else:
            magic_be = struct.unpack(">I", header[:4])[0]
            if magic_be in (PCAP_MAGIC_LE, PCAP_MAGIC_LE_NS):
                endian, ns = ">", magic_be == PCAP_MAGIC_LE_NS
            else:
                raise ValueError(f"{path}: not a classic pcap (magic {magic:#x})")
        linktype = struct.unpack(f"{endian}I", header[20:24])[0]

        while True:
            rec = f.read(16)
            if len(rec) < 16:
                return
            ts_s, ts_frac, incl, orig = struct.unpack(f"{endian}IIII", rec)
            data = f.read(incl)
            if len(data) < incl:
                return
            ts = ts_s + ts_frac / (1e9 if ns else 1e6)
            pkt = parse_frame(data, linktype, ts, orig)
            if pkt is not None:
                yield pkt


def parse_frame(data: bytes, linktype: int, ts: float,
                wire_len: int) -> Optional[PcapPacket]:
    if linktype == LINKTYPE_ETHERNET:
        if len(data) < 14:
            return None
        ethertype = struct.unpack("!H", data[12:14])[0]
        if ethertype != 0x0800:  # IPv4 only
            return None
        ip = data[14:]
    elif linktype == LINKTYPE_LINUX_SLL:
        if len(data) < 16:
            return None
        if struct.unpack("!H", data[14:16])[0] != 0x0800:
            return None
        ip = data[16:]
    elif linktype == LINKTYPE_RAW:
        ip = data
    else:
        return None

    if len(ip) < 20 or (ip[0] >> 4) != 4 or ip[9] != 6:  # v4 + TCP
        return None
    ihl = (ip[0] & 0xF) * 4
    total_len = struct.unpack("!H", ip[2:4])[0]
    src = ".".join(str(b) for b in ip[12:16])
    dst = ".".join(str(b) for b in ip[16:20])
    tcp = ip[ihl:]
    if len(tcp) < 14:
        return None
    sport, dport = struct.unpack("!HH", tcp[:4])
    data_off = (tcp[12] >> 4) * 4
    flags = tcp[13]
    payload_len = max(0, total_len - ihl - data_off)
    return PcapPacket(ts=ts, src=src, dst=dst, sport=sport, dport=dport,
                      flags=flags, payload_len=payload_len, wire_len=wire_len)


# --------------------------------------------------------------------------
# Flow accounting
# --------------------------------------------------------------------------


@dataclass
class FlowStats:
    first_ts: float
    last_ts: float
    packets: int = 0
    bytes: int = 0
    payload_bytes: int = 0
    syns: int = 0
    fins_rsts: int = 0

    @property
    def duration_s(self) -> float:
        return self.last_ts - self.first_ts


FlowKey = Tuple[str, int, str, int]


def canonical(pkt: PcapPacket) -> Tuple[FlowKey, bool]:
    """Direction-collapsed flow key + whether pkt goes in canonical direction."""
    a = (pkt.src, pkt.sport, pkt.dst, pkt.dport)
    b = (pkt.dst, pkt.dport, pkt.src, pkt.sport)
    return (a, True) if a <= b else (b, False)


def analyze_pcap(paths: List[str]) -> Tuple[Dict[FlowKey, FlowStats],
                                            Dict[int, Dict[str, int]]]:
    flows: Dict[FlowKey, FlowStats] = {}
    per_second: Dict[int, Dict[str, int]] = defaultdict(
        lambda: {"packets": 0, "bytes": 0, "new_connections": 0})
    for path in paths:
        for pkt in read_pcap(path):
            key, _ = canonical(pkt)
            st = flows.get(key)
            if st is None:
                st = flows[key] = FlowStats(first_ts=pkt.ts, last_ts=pkt.ts)
            st.packets += 1
            st.bytes += pkt.wire_len
            st.payload_bytes += pkt.payload_len
            st.last_ts = max(st.last_ts, pkt.ts)
            sec = per_second[int(pkt.ts)]
            sec["packets"] += 1
            sec["bytes"] += pkt.wire_len
            if pkt.is_syn:
                st.syns += 1
                sec["new_connections"] += 1
            if pkt.is_fin_or_rst:
                st.fins_rsts += 1
    return flows, dict(per_second)


def load_telemetry_windows(log_dir: str) -> List[dict]:
    """Task windows from telemetry JSONL (task_received .. task_completed)."""
    events = []
    for path in glob.glob(os.path.join(log_dir, "*_agent_a.log")):
        with open(path, encoding="utf-8") as f:
            for line in f:
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    windows: Dict[str, dict] = {}
    for ev in events:
        tid = ev.get("task_id")
        if not tid:
            continue
        w = windows.setdefault(tid, {"task_id": tid})
        if ev.get("event_type") == "task_received":
            w["start_ms"] = ev.get("timestamp_ms")
        elif ev.get("event_type") == "task_completed":
            w["end_ms"] = ev.get("timestamp_ms")
    return [w for w in windows.values() if "start_ms" in w and "end_ms" in w]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("pcaps", nargs="+", help="pcap file(s) from tcpdump -w")
    ap.add_argument("--out-dir", default="data/traffic")
    ap.add_argument("--telemetry-dir",
                    default=os.environ.get("TELEMETRY_LOG_DIR", "logs"))
    args = ap.parse_args()

    flows, per_second = analyze_pcap(args.pcaps)
    os.makedirs(args.out_dir, exist_ok=True)

    with open(os.path.join(args.out_dir, "flows.csv"), "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["src", "sport", "dst", "dport", "packets", "bytes",
                    "payload_bytes", "syns", "fins_rsts", "duration_s"])
        for (src, sport, dst, dport), st in sorted(flows.items()):
            w.writerow([src, sport, dst, dport, st.packets, st.bytes,
                        st.payload_bytes, st.syns, st.fins_rsts,
                        round(st.duration_s, 6)])

    with open(os.path.join(args.out_dir, "per_second.csv"), "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["ts", "packets", "bytes", "new_connections"])
        for sec in sorted(per_second):
            row = per_second[sec]
            w.writerow([sec, row["packets"], row["bytes"],
                        row["new_connections"]])

    windows = load_telemetry_windows(args.telemetry_dir)
    if windows:
        with open(os.path.join(args.out_dir, "task_windows.csv"), "w",
                  newline="") as f:
            w = csv.writer(f)
            w.writerow(["task_id", "start_ms", "end_ms", "bytes_in_window"])
            for win in windows:
                s, e = win["start_ms"] / 1000.0, win["end_ms"] / 1000.0
                total = sum(r["bytes"] for sec, r in per_second.items()
                            if s <= sec <= e)
                w.writerow([win["task_id"], win["start_ms"], win["end_ms"],
                            total])

    print(f"[traffic] {len(flows)} flows, {len(per_second)} seconds, "
          f"{len(windows)} task windows -> {args.out_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
