#!/usr/bin/env bash
# Host prerequisite: Docker CE + compose plugin (reference: scripts/setup/install_docker.sh).
# Debian/Ubuntu only; idempotent.
set -euo pipefail

if command -v docker >/dev/null 2>&1; then
  echo "[setup] docker already installed: $(docker --version)"
else
  echo "[setup] installing Docker CE from download.docker.com"
  sudo apt-get update
  sudo apt-get install -y ca-certificates curl gnupg
  sudo install -m 0755 -d /etc/apt/keyrings
  DISTRO="$(. /etc/os-release && echo "$ID")"   # ubuntu or debian
  curl -fsSL "https://download.docker.com/linux/$DISTRO/gpg" \
    | sudo gpg --dearmor -o /etc/apt/keyrings/docker.gpg
  sudo chmod a+r /etc/apt/keyrings/docker.gpg
  echo "deb [arch=$(dpkg --print-architecture) signed-by=/etc/apt/keyrings/docker.gpg] \
https://download.docker.com/linux/$DISTRO $(. /etc/os-release && echo "$VERSION_CODENAME") stable" \
    | sudo tee /etc/apt/sources.list.d/docker.list >/dev/null
  sudo apt-get update
  sudo apt-get install -y docker-ce docker-ce-cli containerd.io \
    docker-buildx-plugin docker-compose-plugin
fi

# Rootless use for the invoking user.
if ! id -nG "$USER" | grep -qw docker; then
  sudo usermod -aG docker "$USER"
  echo "[setup] added $USER to the docker group (re-login to take effect)"
fi

docker compose version || { echo "[setup] compose plugin missing" >&2; exit 1; }
echo "[setup] docker ready"
