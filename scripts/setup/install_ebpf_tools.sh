#!/usr/bin/env bash
# Host prerequisite: BCC tools + bpftrace for kernel-level TCP observation
# (reference: scripts/setup/install_ebpf_tools.sh). Used by
# scripts/traffic/collect_metrics.sh (tcpconnect/tcplife/tcprtt/tcpretrans)
# and the optional ebpf_exporter programs in infra/monitoring/ebpf_exporter/.
set -euo pipefail

echo "[setup] installing BCC tools + bpftrace (requires kernel headers)"
sudo apt-get update
sudo apt-get install -y bpfcc-tools bpftrace "linux-headers-$(uname -r)" || {
  echo "[setup] exact headers unavailable; trying generic" >&2
  sudo apt-get install -y bpfcc-tools bpftrace linux-headers-generic
}

# Smoke: one-shot tracepoint probe proves the toolchain can attach.
if sudo timeout 5 bpftrace -e 'tracepoint:sock:inet_sock_set_state { exit(); }' \
     >/dev/null 2>&1; then
  echo "[setup] eBPF toolchain functional"
else
  echo "[setup] WARNING: could not attach a probe (container/VM without CAP_BPF?)" >&2
fi
