#!/usr/bin/env bash
# Dynamic endpoint summary from live containers (reference:
# scripts/fetch_endpoints.sh:1-338): prints every service URL an operator
# needs, derived from docker compose ps, with static fallbacks.
set -u

have_docker() { command -v docker >/dev/null 2>&1; }

port_of() {  # $1 container fragment, $2 internal port, $3 fallback
  if have_docker; then
    local p
    p="$(docker ps --filter "name=$1" --format '{{.Ports}}' 2>/dev/null \
        | grep -oE "0\.0\.0\.0:[0-9]+->$2/tcp" | head -1 | sed -E 's/.*:([0-9]+)->.*/\1/')"
    [ -n "$p" ] && { echo "$p"; return; }
  fi
  echo "$3"
}

LLM_PORT="$(port_of llm-backend 8000 8000)"
A_PORT="$(port_of agent-a 8101 8101)"
B_PORT="$(port_of agent-b 8201 8201)"
DB_PORT="$(port_of mcp-tool-db 8301 8301)"
PROXY_PORT="$(port_of openai-proxy 8400 8400)"
UI_PORT="$(port_of ui 3000 3000)"

cat <<EOF
================= testbed endpoints =================
LLM backend   http://localhost:${LLM_PORT}   (/chat /health /metrics)
Agent A       http://localhost:${A_PORT}   (/task /agentverse /health)
Agent B       http://localhost:${B_PORT}   (/subtask /discuss /health)
Tool DB       http://localhost:${DB_PORT}   (/query)
OpenAI proxy  http://localhost:${PROXY_PORT}   (/v1/chat/completions)
Chat UI       http://localhost:${UI_PORT}/chat/
AgentVerse UI http://localhost:${UI_PORT}/agentverse/
Prometheus    http://localhost:9090
Grafana       http://localhost:3001   (anonymous viewer)
Jaeger        http://localhost:16686
TCP metrics   http://localhost:9100/metrics
Mapping exp.  http://localhost:9101/metrics
=====================================================
EOF

if have_docker; then
  echo "running containers:"
  docker ps --format '  {{.Names}}\t{{.Status}}' 2>/dev/null || true
fi
